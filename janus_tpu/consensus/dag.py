"""Narwhal-style DAG mempool as a tensor program.

Reference: BFT-CRDT/DAGConsensus/DAG.cs — per-node threads, dictionaries
and locks: block creation/batching in AdvanceRoundLoop (:720-822), block
validation + signature acks (ReceivedBlock :413-472), certificate
formation at 2f+1 acks (ReceivedSignature :495-568), round advancement at
2f+1 certificates (CheckCertificates :629-714), faulty-rate certificate
withholding (:544-561).

Tensor re-design: an emulated N-node cluster is ONE state pytree; a block
is a (round, source) slot; every protocol rule is a masked reduction:

    edges        bool[W, N, N]   block (r,s) references cert of (r-1,t)
                                 (global truth: edge content is fixed at
                                 creation and travels with the block)
    block_exists bool[W, N]      block (r,s) has been created
    block_seen   bool[N, W, N]   node v has received block (r,s)
    acks         bool[W, N, N]   signer t has acked block (r,s)
    cert_exists  bool[W, N]      2f+1 acks assembled by the creator
    cert_seen    bool[N, W, N]   node v holds the certificate of (r,s)
    node_round   int32[N]        current round per node

Asynchrony — the reference's per-message hand-delivery in its tests
(Tests/DAGTests.cs SimpleDAGMsgTestSender) — is expressed by *delivery
masks*: each phase function takes an optional bool mask selecting which
(recipient, round, source) messages land this call. Passing no mask gives
the synchronous fast path (everything delivers), which is one XLA program
per round. Equivocation is structurally impossible here (one slot per
(round, source)); invalid-block pruning reduces to the structural
validity mask. W is a static round window; quorum = 2f+1, f=(n-1)//3
(DAG.cs:117).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax.numpy as jnp

State = Dict[str, jnp.ndarray]


@dataclasses.dataclass(frozen=True)
class DagConfig:
    num_nodes: int
    num_rounds: int  # static window W

    @property
    def f(self) -> int:
        return (self.num_nodes - 1) // 3

    @property
    def quorum(self) -> int:
        return 2 * self.f + 1


def init(cfg: DagConfig) -> State:
    n, w = cfg.num_nodes, cfg.num_rounds
    return {
        "edges": jnp.zeros((w, n, n), bool),
        "block_exists": jnp.zeros((w, n), bool),
        "block_seen": jnp.zeros((n, w, n), bool),
        "acks": jnp.zeros((w, n, n), bool),
        "cert_exists": jnp.zeros((w, n), bool),
        "cert_seen": jnp.zeros((n, w, n), bool),
        "node_round": jnp.zeros((n,), jnp.int32),
    }


def _all_mask(cfg: DagConfig):
    return jnp.ones((cfg.num_nodes, cfg.num_rounds, cfg.num_nodes), bool)


def create_blocks(cfg: DagConfig, state: State, active: Optional[jnp.ndarray] = None) -> State:
    """Each active node at round r creates its (r, v) block if it hasn't:
    genesis blocks (r=0) reference nothing; later blocks reference every
    certificate the creator holds for round r-1 (the reference includes
    >=2f+1 prev certs — round advancement guarantees that many are held,
    DAG.cs:774-812). The creator sees its own block and self-acks
    (CreateBlock self-signature, DAG.cs:896-906)."""
    n = cfg.num_nodes
    vs = jnp.arange(n)
    r = state["node_round"]
    act = jnp.ones((n,), bool) if active is None else active
    fresh = act & ~state["block_exists"][r, vs] & (r < cfg.num_rounds)

    prev_r = jnp.maximum(r - 1, 0)
    prev_certs = state["cert_seen"][vs, prev_r, :]  # [N, N]
    new_edges = jnp.where((fresh & (r > 0))[:, None], prev_certs, False)

    out = dict(state)
    out["block_exists"] = state["block_exists"].at[r, vs].max(fresh)
    out["edges"] = state["edges"].at[r, vs, :].max(new_edges)
    out["block_seen"] = state["block_seen"].at[vs, r, vs].max(fresh)
    out["acks"] = state["acks"].at[r, vs, vs].max(fresh)
    return out


def deliver_blocks(cfg: DagConfig, state: State, mask: Optional[jnp.ndarray] = None) -> State:
    """Broadcast: node v receives block (r,s) where mask allows and the
    block exists (mask axes: [recipient, round, source])."""
    m = _all_mask(cfg) if mask is None else mask
    out = dict(state)
    out["block_seen"] = state["block_seen"] | (m & state["block_exists"][None])
    return out


def structural_validity(cfg: DagConfig, state: State) -> jnp.ndarray:
    """bool[W, N]: genesis blocks are valid; later blocks need >=2f+1
    embedded prev-certificate references (the receive-side check of
    ReceivedBlock, DAG.cs:413-472 — certs travel inside the block, so the
    check is structural)."""
    refs = jnp.sum(state["edges"], axis=-1)  # [W, N]
    rounds = jnp.arange(cfg.num_rounds)[:, None]
    return (rounds == 0) | (refs >= cfg.quorum)


def sign_blocks(cfg: DagConfig, state: State, mask: Optional[jnp.ndarray] = None) -> State:
    """Every node acks each valid block it has seen; the signature is
    delivered to the block's creator where mask allows (mask axes:
    [signer, round, source])."""
    m = _all_mask(cfg) if mask is None else mask
    valid = structural_validity(cfg, state)  # [W, N]
    sigs = state["block_seen"] & valid[None] & m  # [signer, W, N]
    out = dict(state)
    out["acks"] = state["acks"] | jnp.transpose(sigs, (1, 2, 0))
    return out


def form_certificates(cfg: DagConfig, state: State, withhold: Optional[jnp.ndarray] = None) -> State:
    """A certificate exists once 2f+1 signatures are assembled
    (ReceivedSignature quorum check, DAG.cs:520). ``withhold[W, N]``
    suppresses certificate formation/broadcast by faulty creators — the
    faultyRate Byzantine knob (DAG.cs:544-561). The creator immediately
    holds its own certificate."""
    n = cfg.num_nodes
    counts = jnp.sum(state["acks"], axis=-1)  # [W, N]
    formed = counts >= cfg.quorum
    if withhold is not None:
        formed = formed & ~withhold
    out = dict(state)
    out["cert_exists"] = state["cert_exists"] | formed
    # own[v, r, s] = (v == s) & cert_exists[r, s] — creator holds its cert
    own = out["cert_exists"][None, :, :] & (
        jnp.arange(n)[:, None, None] == jnp.arange(n)[None, None, :]
    )
    out["cert_seen"] = state["cert_seen"] | own
    return out


def deliver_certificates(cfg: DagConfig, state: State, mask: Optional[jnp.ndarray] = None) -> State:
    """Certificate broadcast (mask axes: [recipient, round, source])."""
    m = _all_mask(cfg) if mask is None else mask
    out = dict(state)
    out["cert_seen"] = state["cert_seen"] | (m & state["cert_exists"][None])
    return out


def advance_rounds(cfg: DagConfig, state: State) -> State:
    """A node advances past round r once it holds 2f+1 certificates for
    round-r blocks (CheckCertificates round-advance signal,
    DAG.cs:629-714)."""
    n = cfg.num_nodes
    vs = jnp.arange(n)
    r = state["node_round"]
    have = jnp.sum(state["cert_seen"][vs, r, :], axis=-1)
    ready = (have >= cfg.quorum) & (r + 1 < cfg.num_rounds)
    out = dict(state)
    out["node_round"] = r + ready.astype(jnp.int32)
    return out


def round_step(cfg: DagConfig, state: State, active: Optional[jnp.ndarray] = None,
               withhold: Optional[jnp.ndarray] = None) -> State:
    """One synchronous protocol round: create -> broadcast -> sign ->
    certify -> broadcast -> advance. With no masks this is the
    full-delivery fast path (the whole cluster moves one round per call);
    ``active``/``withhold`` model crashed and certificate-withholding
    nodes. Crashed nodes neither create, sign, nor receive."""
    act_mask = None
    wh = withhold
    if active is not None:
        act_mask = active[:, None, None] & _all_mask(cfg)
        # a crashed creator cannot aggregate acks into a certificate
        # (signatures return to the creator, ReceivedSignature
        # DAG.cs:495-568) — treat it as withholding while down
        crash_wh = jnp.broadcast_to(
            ~active[None, :], (cfg.num_rounds, cfg.num_nodes)
        )
        wh = crash_wh if wh is None else (wh | crash_wh)
    state = create_blocks(cfg, state, active)
    state = deliver_blocks(cfg, state, act_mask)
    state = sign_blocks(cfg, state, act_mask)
    state = form_certificates(cfg, state, wh)
    state = deliver_certificates(cfg, state, act_mask)
    state = advance_rounds(cfg, state)
    return state
