"""DAG mempool (Narwhal-style) + Tusk wave commit as tensor programs."""

from janus_tpu.consensus.dag import (  # noqa: F401
    DagConfig,
    advance_rounds,
    create_blocks,
    deliver_blocks,
    deliver_certificates,
    form_certificates,
    init,
    recycle,
    round_step,
    sign_blocks,
    slot_of,
    structural_validity,
)
from janus_tpu.consensus.tusk import (  # noqa: F401
    commit_view,
    init_commit,
    leader_of,
    leaders,
    order_key,
    ordered_blocks,
    recycle_commit,
)
