"""Pure lattice-join kernels — the compute core of the framework.

Replaces the reference's per-dictionary sequential merges
(reference: MergeSharp/MergeSharp/CRDTs/PNCounters.cs:131-144,
ORSet.cs:253-283, LWWSet.cs:255-300, MVRegister.cs:168-206) with
fixed-shape, batched tensor kernels that XLA can tile onto the VPU/MXU.
"""

from janus_tpu.ops.lattice import (
    SENTINEL,
    join_max,
    join_or,
    clock_leq,
    clock_dominates,
    clock_compare,
    ts_after,
    ts_max,
)
from janus_tpu.ops.setops import (
    mark_members,
    slot_union,
    row_find,
    row_first_free,
    row_upsert,
    row_insert,
    make_slots,
)

__all__ = [
    "SENTINEL",
    "join_max",
    "join_or",
    "clock_leq",
    "clock_dominates",
    "clock_compare",
    "ts_after",
    "ts_max",
    "slot_union",
    "mark_members",
    "row_find",
    "row_first_free",
    "row_upsert",
    "row_insert",
    "make_slots",
]
