"""Elementwise lattice joins and vector-clock algebra.

These are the innermost kernels of the framework. In the reference, the
equivalents are sequential dictionary walks executed per object on the CPU
(PN-Counter max-join at PNCounters.cs:131-144 — 52.3% of saturated server
CPU per the paper's §6.4 profile; MVRegister clock compare at
MVRegister.cs:168-206). Here they are shape-polymorphic jnp ops that batch
over (replicas x keys x clock-slots) and fuse under jit.
"""
from __future__ import annotations

import jax.numpy as jnp

# Reserved key value marking an empty slot in slot-set tensors. Sorts after
# every real key, so compaction pushes free slots to the tail. Real keys /
# interned element ids must be < SENTINEL (utils.ids guarantees this).
SENTINEL = jnp.iinfo(jnp.int32).max


def join_max(a, b):
    """Grow-only-vector join: elementwise max.

    The PN-Counter / LWW lattice join (reference PNCounters.cs:131-144:
    ``p[i] = max(p[i], other.p[i])`` looped per dictionary entry).
    """
    return jnp.maximum(a, b)


def join_or(a, b):
    """Boolean-lattice join: elementwise OR (set-union on bitmaps,
    tombstone propagation, DAG reachability joins)."""
    return jnp.logical_or(a, b)


# ---------------------------------------------------------------------------
# Vector clocks. A clock is an int32 tensor [..., C] with one slot per
# potential writer (the dense analog of the reference's Dictionary<Guid,int>
# at MVRegister.cs:73; an absent entry is 0).
# ---------------------------------------------------------------------------

def clock_leq(a, b):
    """True where clock ``a`` happens-before-or-equals ``b`` (a <= b
    elementwise over the trailing clock axis)."""
    return jnp.all(a <= b, axis=-1)


def clock_dominates(a, b):
    """True where ``a`` strictly dominates ``b`` (b <= a and b != a)."""
    return clock_leq(b, a) & jnp.any(a > b, axis=-1)


# Comparison codes (reference MVRegister.ComparisonResults,
# MVRegister.cs:78-92, made symmetric):
CLOCK_EQUAL = 0
CLOCK_BEFORE = 1      # a happens-before b  -> b overwrites
CLOCK_AFTER = 2       # b happens-before a  -> a wins
CLOCK_CONCURRENT = 3  # concurrent          -> merge


def clock_compare(a, b):
    """Classify clock pairs along the trailing axis -> int32 code tensor."""
    ale = clock_leq(a, b)
    ble = clock_leq(b, a)
    return jnp.where(
        ale & ble,
        CLOCK_EQUAL,
        jnp.where(ale, CLOCK_BEFORE, jnp.where(ble, CLOCK_AFTER, CLOCK_CONCURRENT)),
    ).astype(jnp.int32)


# ---------------------------------------------------------------------------
# 64-bit timestamps as (hi, lo) int32 pairs. TPUs prefer 32-bit lanes; the
# reference's DateTime ticks (LWWSet.cs:148-191) become a split counter with
# lexicographic order.
# ---------------------------------------------------------------------------

_SIGN = jnp.int32(-(2**31))


def ts_after(hi_a, lo_a, hi_b, lo_b):
    """True where timestamp a >= b (lexicographic on (hi, lo)).

    ">=" so that on equal stamps the first operand wins — the add-wins tie
    rule of the reference LWW set (LWWSet.cs lookup: add beats remove on
    ties) is expressed by passing the add stamp as ``a``. The low word is
    an unsigned 32-bit counter; flipping the sign bit makes the signed
    compare behave unsigned.
    """
    ua, ub = lo_a ^ _SIGN, lo_b ^ _SIGN
    return (hi_a > hi_b) | ((hi_a == hi_b) & (ua >= ub))


def ts_max(hi_a, lo_a, hi_b, lo_b):
    """Lexicographic max of (hi, lo) timestamp pairs -> (hi, lo)."""
    take_a = ts_after(hi_a, lo_a, hi_b, lo_b)
    return jnp.where(take_a, hi_a, hi_b), jnp.where(take_a, lo_a, lo_b)
