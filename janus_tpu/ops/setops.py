"""Fixed-capacity slot-set tensors and their sorted-union join.

The reference stores CRDT sets as ``Dictionary<T, HashSet<Guid>>`` and merges
them with nested hash-walks (ORSet.cs:253-283, LWWSet.cs:255-300,
2P-Set.cs:188-192). On TPU, a set lives in a *slot tensor*: ``[..., C]``
arrays of int32 key fields plus payload fields, with a boolean ``valid``
mask. Union is a data-parallel sort-based kernel:

    concat -> lexicographic lax.sort on key fields -> fold adjacent
    duplicates with a payload-combine -> stable compaction sort.

Everything is static-shape and batches over arbitrary leading axes
(replicas, keys), so XLA lays it onto the VPU; no per-element host loop.

Invariants
----------
- Within one slot set, each valid slot has a unique key tuple (so after
  concatenating two sets a key appears at most twice, making the
  single-neighbor duplicate fold exact).
- Key fields are int32 and < SENTINEL; invalid slots are canonicalized to
  SENTINEL so they sort to the tail.
"""
from __future__ import annotations

from typing import Callable, Dict, Sequence, Tuple

import jax.numpy as jnp
from jax import lax

from janus_tpu.ops.lattice import SENTINEL

Slots = Dict[str, jnp.ndarray]  # field -> [..., C]; must contain "valid"


def make_slots(
    capacity: int,
    fields: Dict[str, jnp.dtype],
    batch: Tuple[int, ...] = (),
    key_fields: Sequence[str] = (),
) -> Slots:
    """Allocate an empty slot set: all slots invalid.

    Canonical-form contract (relied on by state-digest / convergence
    comparisons): invalid slots hold SENTINEL in key fields and 0 in
    payload fields — the same fill ``slot_union`` re-establishes on its
    output. If ``key_fields`` is empty, every int32 field is treated as a
    key (the pre-batching callers' behavior).
    """
    keys = set(key_fields)
    out: Slots = {"valid": jnp.zeros(batch + (capacity,), dtype=bool)}
    for name, dt in fields.items():
        is_key = name in keys if keys else jnp.issubdtype(dt, jnp.int32)
        out[name] = jnp.full(batch + (capacity,), SENTINEL if is_key else 0, dtype=dt)
    return out


def _canon_keys(s: Slots, key_fields: Sequence[str]):
    return [jnp.where(s["valid"], s[f], SENTINEL) for f in key_fields]


def slot_union(
    a: Slots,
    b: Slots,
    key_fields: Sequence[str],
    combine: Callable[[Dict, Dict], Dict],
    capacity: int | None = None,
):
    """Join two slot sets by key-union; duplicate keys fold payloads.

    ``combine(p, q) -> dict`` merges the payload fields of two slots with
    equal keys (e.g. OR for tombstones, lexicographic-max for timestamps).
    Returns ``(out_slots, overflow)`` where ``overflow[...]`` counts kept
    slots that did not fit in ``capacity`` (the moral replacement for the
    reference's unbounded OR-Set growth — 196 MB messages per paper §6.2 —
    is to size capacity and compact, not to grow).
    """
    nk = len(key_fields)
    cap = capacity if capacity is not None else max(
        a[key_fields[0]].shape[-1], b[key_fields[0]].shape[-1]
    )
    payload_fields = [f for f in a if f != "valid" and f not in key_fields]

    cat_keys = [
        jnp.concatenate([ka, kb], axis=-1)
        for ka, kb in zip(_canon_keys(a, key_fields), _canon_keys(b, key_fields))
    ]
    cat_valid = jnp.concatenate([a["valid"], b["valid"]], axis=-1)
    cat_pay = [jnp.concatenate([a[f], b[f]], axis=-1) for f in payload_fields]

    sorted_ops = lax.sort(
        tuple(cat_keys) + (cat_valid,) + tuple(cat_pay),
        dimension=-1,
        num_keys=nk,
        is_stable=True,
    )
    skeys = sorted_ops[:nk]
    svalid = sorted_ops[nk]
    spay = {f: arr for f, arr in zip(payload_fields, sorted_ops[nk + 1:])}

    # dup[i]: slot i carries the same key as slot i-1 (both valid).
    same = svalid & jnp.roll(svalid, 1, axis=-1)
    for k in skeys:
        same = same & (k == jnp.roll(k, 1, axis=-1))
    same = same.at[..., 0].set(False)
    dup = same

    # Fold the payload of a duplicate into its predecessor (the kept copy).
    nxt_dup = jnp.concatenate([dup[..., 1:], jnp.zeros_like(dup[..., :1])], axis=-1)
    nxt_pay = {f: jnp.roll(v, -1, axis=-1) for f, v in spay.items()}
    folded = combine(spay, nxt_pay)
    pay = {f: jnp.where(nxt_dup, folded[f], spay[f]) for f in payload_fields}
    keep = svalid & ~dup

    # Stable compaction: kept slots to the front, preserving key order.
    rank = (~keep).astype(jnp.int32)
    ops2 = (
        (rank,)
        + tuple(jnp.where(keep, k, SENTINEL) for k in skeys)
        + (keep,)
        + tuple(pay[f] for f in payload_fields)
    )
    sorted2 = lax.sort(ops2, dimension=-1, num_keys=1, is_stable=True)
    out_keys = sorted2[1 : 1 + nk]
    out_valid = sorted2[1 + nk]
    out_pays = sorted2[2 + nk :]

    def fit(arr, fill):
        """Slice or SENTINEL-pad the trailing axis to exactly ``cap``."""
        n = arr.shape[-1]
        if n >= cap:
            return arr[..., :cap]
        pad = jnp.full(arr.shape[:-1] + (cap - n,), fill, dtype=arr.dtype)
        return jnp.concatenate([arr, pad], axis=-1)

    # Canonicalize: invalid slots carry SENTINEL keys and zero payloads so
    # that equal sets are bit-equal tensors (state digests / convergence
    # asserts compare raw arrays).
    valid = fit(out_valid, False)
    out: Slots = {"valid": valid}
    for f, arr in zip(key_fields, out_keys):
        out[f] = jnp.where(valid, fit(arr, SENTINEL), SENTINEL)
    for f, arr in zip(payload_fields, out_pays):
        fitted = fit(arr, 0)
        out[f] = jnp.where(valid, fitted, jnp.zeros_like(fitted))
    overflow = jnp.sum(keep, axis=-1) - jnp.sum(out["valid"], axis=-1)
    return out, overflow


# ---------------------------------------------------------------------------
# Single-row helpers for op application (used under lax.scan when a batch of
# client ops targets individual key rows). Rows are [C] slices.
# ---------------------------------------------------------------------------

def row_find(row: Slots, key_fields: Sequence[str], key_vals: Sequence[jnp.ndarray]):
    """Locate a key in a row -> (found: bool, idx: int32). idx is arbitrary
    when not found."""
    hit = row["valid"]
    for f, v in zip(key_fields, key_vals):
        hit = hit & (row[f] == v)
    return jnp.any(hit), jnp.argmax(hit).astype(jnp.int32)


def row_first_free(row: Slots):
    """First invalid slot -> (has_free: bool, idx: int32)."""
    free = ~row["valid"]
    return jnp.any(free), jnp.argmax(free).astype(jnp.int32)


def row_insert(row: Slots, values: Dict[str, jnp.ndarray], enabled=True,
               stats: Dict[str, jnp.ndarray] | None = None):
    """Insert a slot into the first free position. Drops when full; a
    caller that passes a ``stats`` dict gets the drop accumulated
    device-side into ``stats["slots_dropped"]`` (an int32 scalar it
    threads through its scan carry and surfaces to the obs metrics
    registry after fetch) — without one the drop stays silent, as
    before."""
    has_free, idx = row_first_free(row)
    en = jnp.asarray(enabled)
    do = en & has_free
    if stats is not None:
        stats["slots_dropped"] = (
            stats.get("slots_dropped", jnp.int32(0))
            + (en & ~has_free).astype(jnp.int32))
    out = dict(row)
    for f, v in values.items():
        out[f] = jnp.where(do, row[f].at[idx].set(v), row[f])
    out["valid"] = jnp.where(do, row["valid"].at[idx].set(True), row["valid"])
    return out


def row_upsert(
    row: Slots,
    key_fields: Sequence[str],
    key_vals: Sequence[jnp.ndarray],
    values: Dict[str, jnp.ndarray],
    combine_existing: Callable[[Dict, Dict], Dict],
    enabled=True,
    stats: Dict[str, jnp.ndarray] | None = None,
):
    """Insert a key or fold ``values`` into its existing slot.

    ``combine_existing(old_payload, new_payload) -> payload`` decides the
    update for an existing key (e.g. timestamp max for LWW adds).
    ``stats`` (optional) accumulates ``slots_dropped`` like row_insert —
    here a drop is an enabled upsert of an ABSENT key into a full row
    (folding into an existing slot never drops).
    """
    found, idx = row_find(row, key_fields, key_vals)
    en = jnp.asarray(enabled)
    if stats is not None:
        has_free, _ = row_first_free(row)
        stats["slots_dropped"] = (
            stats.get("slots_dropped", jnp.int32(0))
            + (en & ~found & ~has_free).astype(jnp.int32))

    # Path 1: fold into existing slot.
    old = {f: row[f][idx] for f in row if f != "valid" and f not in key_fields}
    new = combine_existing(old, values)
    updated = dict(row)
    for f, v in new.items():
        updated[f] = row[f].at[idx].set(v)

    # Path 2: fresh insert.
    ins_vals = dict(values)
    for f, v in zip(key_fields, key_vals):
        ins_vals[f] = v
    inserted = row_insert(row, ins_vals, enabled=en)

    out = {}
    for f in row:
        out[f] = jnp.where(
            en & found, updated[f], jnp.where(en, inserted[f], row[f])
        )
    return out


def mark_members(
    a_keys: Sequence[jnp.ndarray],
    b_keys: Sequence[jnp.ndarray],
    b_valid: jnp.ndarray,
) -> jnp.ndarray:
    """bool[M]: does A record i's 2-part key equal some valid B key?

    One sort-merge over M+T records instead of an O(M*T) compare matrix —
    the membership primitive compaction fences use to protect slots whose
    tag/id is still referenced by a live consensus op. Keys are int32
    pairs < SENTINEL (A records keyed SENTINEL — invalid slots — only
    match B SENTINELs, which ``b_valid`` masks out)."""
    k1a, k2a = a_keys
    k1b, k2b = b_keys
    m, t = k1a.shape[0], k1b.shape[0]
    # degenerate static shapes: no A records -> nothing to mark; no B
    # records -> nothing can match (the cumsum/segment machinery below
    # assumes total >= 1 — seg_end would index an empty csum)
    if m == 0 or t == 0:
        return jnp.zeros((m,), bool)
    total = m + t
    k1 = jnp.concatenate([k1a, jnp.where(b_valid, k1b, SENTINEL)])
    k2 = jnp.concatenate([k2a, jnp.where(b_valid, k2b, SENTINEL)])
    is_b = jnp.concatenate([jnp.zeros((m,), bool), b_valid])
    orig = jnp.concatenate([
        jnp.arange(m, dtype=jnp.int32), jnp.full((t,), m, jnp.int32)
    ])
    # LSD argsort via two stable single-key passes (cheapest multi-key
    # sort shape on TPU; see orset._apply_captured_batch)
    idx = jnp.arange(total, dtype=jnp.int32)
    _, idx = lax.sort((k2, idx), dimension=-1, num_keys=1, is_stable=True)
    _, idx = lax.sort((k1[idx], idx), dimension=-1, num_keys=1,
                      is_stable=True)
    k1s, k2s = k1[idx], k2[idx]
    is_bs, origs = is_b[idx], orig[idx]
    first = jnp.ones((total,), bool).at[1:].set(
        (k1s[1:] != k1s[:-1]) | (k2s[1:] != k2s[:-1]))
    # segment-OR of is_b via cumsum differences at segment bounds
    ii = jnp.arange(total, dtype=jnp.int32)
    bi = is_bs.astype(jnp.int32)
    csum = jnp.cumsum(bi)
    nxt_first = lax.cummin(jnp.where(first, ii, total), reverse=True)
    seg_end = jnp.concatenate(
        [nxt_first[1:], jnp.asarray([total], jnp.int32)]) - 1
    seg_start = lax.cummax(jnp.where(first, ii, 0))
    excl_at_start = (csum - bi)[seg_start]
    seg_has_b = (csum[jnp.clip(seg_end, 0, total - 1)] - excl_at_start) > 0
    return jnp.zeros((m + 1,), bool).at[origs].max(seg_has_b)[:m]
