"""Out-of-band SLO plane (ISSUE 12): per-op e2e latency ledger, the
obs HTTP endpoint, and cluster obs federation. The contracts under
test:

- classify() maps wire op codes to the three consistency classes the
  paper's latency contracts name (unsafe / safe / stable);
- SloLedger counts every reply and records latency only for stamped
  ops (t0_ns <= 0 = old client / v1 frame: counted, never sampled);
- merge_slo sums bucket VECTORS and recomputes percentiles from the
  merged counts (percentile-of-percentiles would be wrong), keeping
  per-node attribution;
- the service's out-of-band endpoint serves /slo//health//metrics
  without riding the data plane, and its ledger reconciles exactly
  with the ops a client actually sent — unsharded and sharded (where
  /slo additionally carries per-shard nodes);
- a hand-built v1 batch frame (no t0 header) still applies its ops and
  counts as unstamped;
- the merge helpers tolerate degenerate input: empty lists, disjoint
  key sets (version skew), unknown health statuses, dead federation
  peers;
- watchdogs sharing a dump_dir qualify their flight-dump filenames
  with the configured tag instead of overwriting each other.
"""
import json
import socket
import struct
import time

import numpy as np

from janus_tpu.net import JanusClient, JanusConfig, JanusService, TypeConfig
from janus_tpu.net.client import BatchSender, frame0
from janus_tpu.net.service import _merge_type_stats
from janus_tpu.obs import flight
from janus_tpu.obs.export import render_prometheus
from janus_tpu.obs.httpexp import (ObsHttpServer, federation_routes,
                                   merge_prometheus, scrape_json,
                                   scrape_text)
from janus_tpu.obs.metrics import (Histogram, Registry, get_registry,
                                   percentile_from_counts)
from janus_tpu.obs.slo import OP_CLASSES, SloLedger, classify, merge_slo
from janus_tpu.obs.watchdog import (HealthWatchdog, WatchdogConfig,
                                    merge_health)

KEYS = [f"o{k}" for k in range(4)]


# -- op classification ----------------------------------------------------


def test_classify_covers_the_three_contracts():
    assert classify("gs", False) == "stable"
    assert classify("ss", True) == "stable"
    assert classify("gp", False) == "unsafe"
    assert classify("sp", False) == "unsafe"
    assert classify("g", False) == "unsafe"
    assert classify("i", False) == "unsafe"
    assert classify("i", True) == "safe"
    assert classify("s", True) == "safe"
    assert set(OP_CLASSES) == {"unsafe", "safe", "stable"}


# -- ledger unit behavior -------------------------------------------------


def test_ledger_unstamped_counts_but_never_samples():
    led = SloLedger(registry=Registry())
    led.observe("unsafe", 0)
    led.observe("unsafe", -5)
    snap = led.snapshot()
    assert snap["classes"]["unsafe"]["replied"] == 2
    assert snap["classes"]["unsafe"]["e2e_samples"] == 0
    assert snap["replied_total"] == 2


def test_ledger_stamped_records_the_delta():
    led = SloLedger(registry=Registry())
    led.observe("safe", 1_000, now_ns=5_000)
    snap = led.snapshot()["classes"]["safe"]
    assert snap["replied"] == 1
    assert snap["e2e_samples"] == 1
    # 4000 ns lands in bucket [2^11, 2^12)
    assert snap["counts"][12] == 1


def test_ledger_batch_mixed_stamped_and_unstamped():
    led = SloLedger(registry=Registry())
    t0s = np.array([1_000, 0, 2_000, -1], np.int64)
    led.observe_batch("unsafe", t0s, now_ns=10_000)
    snap = led.snapshot()["classes"]["unsafe"]
    assert snap["replied"] == 4
    assert snap["e2e_samples"] == 2  # only the two stamped ops


def test_ledger_batch_all_stamped_fast_path():
    led = SloLedger(registry=Registry())
    led.observe_batch("unsafe", np.full(64, 1_000, np.int64),
                      now_ns=9_000)
    snap = led.snapshot()["classes"]["unsafe"]
    assert snap["replied"] == 64
    assert snap["e2e_samples"] == 64
    assert snap["counts"][13] == 64  # 8000 ns -> bucket [2^12, 2^13)


def test_ledger_batch_empty_is_a_noop():
    led = SloLedger(registry=Registry())
    led.observe_batch("unsafe", np.array([], np.int64))
    assert led.snapshot()["replied_total"] == 0


def test_ledger_scope_lands_in_instrument_names():
    reg = Registry()
    SloLedger(scope="_s3", registry=reg).observe("unsafe", 0)
    assert reg.counter("slo_s3_replied_unsafe_total").value == 1


def test_record_many_matches_scalar_record_buckets():
    """The vectorized path (frexp + bincount) must bucket EXACTLY like
    the scalar bit_length path — the merged percentiles depend on it."""
    vals = [0, 1, 2, 3, 7, 8, 1023, 1024, 123_456_789, 2**61, 2**63 - 1]
    a, b = Histogram("_a"), Histogram("_b")
    for v in vals:
        a.record(v)
    b.record_many(np.array(vals, np.uint64).astype(np.int64))
    # 2**63 - 1 as int64 stays positive; both paths clip to the top
    assert a.counts() == b.counts()
    assert a.count == b.count


# -- merge_slo ------------------------------------------------------------


def test_merge_slo_sums_buckets_and_recomputes_percentiles():
    r0, r1 = Registry(), Registry()
    led0, led1 = SloLedger(registry=r0), SloLedger(registry=r1)
    # shard 0 is fast (bucket ~2^10 ns), shard 1 slow (~2^20 ns)
    led0.observe_batch("unsafe", np.full(90, 1_000, np.int64),
                       now_ns=2_000)
    led1.observe_batch("unsafe", np.full(10, 1_000, np.int64),
                       now_ns=1_000_000)
    led0.offered.add(90)
    led1.offered.add(10)
    merged = merge_slo([("s0", led0.snapshot()), ("s1", led1.snapshot())])
    cl = merged["classes"]["unsafe"]
    assert cl["replied"] == 100 and cl["e2e_samples"] == 100
    assert merged["offered"] == 100
    # p50 must come from the fast mass, p99 from the slow shard's
    # bucket — averaging per-shard percentiles could produce neither
    assert cl["e2e_p50_ms"] < 0.01
    assert cl["e2e_p99_ms"] > 0.5
    # per-node attribution survives, sans the bulky bucket vectors
    assert merged["nodes"]["s1"]["classes"]["unsafe"]["replied"] == 10
    assert "counts" not in merged["nodes"]["s0"]["classes"]["unsafe"]


def test_merge_slo_empty_and_missing_classes():
    merged = merge_slo([])
    assert merged["replied_total"] == 0
    assert merged["classes"]["unsafe"]["e2e_p99_ms"] == 0.0
    # a version-skewed snapshot missing whole sections still folds
    merged = merge_slo([("x", {"offered": 3})])
    assert merged["offered"] == 3
    assert merged["nodes"]["x"]["offered"] == 3


# -- stats / health merge degenerates ------------------------------------


def test_merge_type_stats_empty_list_is_empty():
    assert _merge_type_stats([]) == {}


def test_merge_type_stats_single_snapshot_is_identity():
    snap = {"pending_ops": 3, "block_size": 64, "window": 8}
    assert _merge_type_stats([snap]) == snap


def test_merge_type_stats_unions_disjoint_key_sets():
    # version skew: one shard reports a counter the other doesn't have
    merged = _merge_type_stats([{"pending_ops": 2},
                                {"pending_ops": 3, "new_counter": 7}])
    assert merged["pending_ops"] == 5
    assert merged["new_counter"] == 7


def test_merge_health_empty_is_ok():
    merged = merge_health([])
    assert merged["status"] == "OK"
    assert merged["reasons"] == [] and merged["anomalies"] == 0


def test_merge_health_worst_of_with_labeled_reasons():
    merged = merge_health([
        ("s0", {"status": "OK", "reasons": [], "anomalies": 0,
                "dumps": 1, "equivocation": {}}),
        ("s1", {"status": "STALLED",
                "reasons": ["commit_stall:pnc -> STALLED: wedged"],
                "anomalies": 2, "dumps": 3, "equivocation": {2: 5}}),
    ])
    assert merged["status"] == "STALLED"
    assert merged["anomalies"] == 2 and merged["dumps"] == 4
    assert merged["reasons"] == ["s1: commit_stall:pnc -> STALLED: wedged"]
    assert merged["equivocation"] == {"s1:2": 5}


def test_merge_health_unknown_status_degrades_not_trusted():
    merged = merge_health([("p0", {"status": "WEIRD", "reasons": []})])
    assert merged["status"] == "DEGRADED"
    assert any("unknown status" in r for r in merged["reasons"])


# -- federation -----------------------------------------------------------


def test_merge_prometheus_splices_node_label():
    text = merge_prometheus([
        ("s0", "# HELP x ops\n# TYPE x counter\nx 3\n"),
        ("s1", "# TYPE x counter\nx{a=\"b\"} 4\n"),
    ])
    assert 'x{node="s0"} 3' in text
    assert 'x{node="s1",a="b"} 4' in text
    assert text.count("# TYPE x counter") == 1


def test_federation_survives_a_dead_peer():
    reg = Registry()
    led = SloLedger(registry=reg)
    led.observe("unsafe", 1_000, now_ns=3_000)
    wd = HealthWatchdog(registry=reg)
    peer = ObsHttpServer({
        "/metrics": lambda: ("text/plain", render_prometheus(reg)),
        "/slo": lambda: ("application/json", json.dumps(led.snapshot())),
        "/health": lambda: ("application/json", json.dumps(wd.health())),
    }, registry=reg)
    # port 1 refuses connections: a wedged/absent worker host
    front = ObsHttpServer(federation_routes(
        [("live", f"http://127.0.0.1:{peer.port}"),
         ("dead", "http://127.0.0.1:1")], timeout=0.5), registry=reg)
    base = f"http://127.0.0.1:{front.port}"
    try:
        text = scrape_text(base + "/metrics")
        assert 'obs_peer_up{node="live"} 1' in text
        assert 'obs_peer_up{node="dead"} 0' in text
        assert 'slo_replied_unsafe_total{node="live"} 1' in text
        slo = scrape_json(base + "/slo")
        assert slo["classes"]["unsafe"]["replied"] == 1
        assert slo["up"] == {"live": True, "dead": False}
        health = scrape_json(base + "/health")
        # the dead peer is a DEGRADED verdict of its own, not a scrape
        # failure — the cluster verdict escalates instead of erroring
        assert health["status"] == "DEGRADED"
        assert any("dead" in r and "unreachable" in r
                   for r in health["reasons"])
    finally:
        front.close()
        peer.close()


def test_obs_endpoint_404_and_handler_errors_keep_serving():
    reg = Registry()

    def boom():
        raise RuntimeError("handler bug")

    srv = ObsHttpServer({"/boom": boom,
                         "/ok": lambda: ("text/plain", "fine\n")},
                        registry=reg)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        for path, want in (("/nope", 404), ("/boom", 500)):
            try:
                scrape_text(base + path)
                raise AssertionError("expected HTTPError")
            except Exception as e:
                assert getattr(e, "code", None) == want, (path, e)
        assert scrape_text(base + "/ok") == "fine\n"
        assert reg.counter("obs_http_errors_total").value == 1
    finally:
        srv.close()


# -- watchdog dump-file tags ---------------------------------------------


def test_watchdog_tag_qualifies_dump_filenames(tmp_path):
    rec = flight.enable()
    rec.clear()
    try:
        wds = [HealthWatchdog(WatchdogConfig(stall_ticks=2,
                                             dump_dir=str(tmp_path),
                                             tag=f"s{i}"),
                              registry=Registry(), recorder=rec)
               for i in range(2)]
        for wd in wds:
            for _ in range(3):
                wd.observe_commits("pnc", own_commits=5, pending_ops=9)
        names = sorted(p.name for p in tmp_path.iterdir())
        # without the tag both would write flight_commit_stall_1.jsonl
        # and shard 1 would silently overwrite shard 0's evidence
        assert names == ["flight_commit_stall_s0_1.jsonl",
                         "flight_commit_stall_s1_1.jsonl"]
    finally:
        flight.disable()


# -- end-to-end: service obs endpoint + wire t0 ---------------------------


def _mk_service(shards: int) -> JanusService:
    # the service ledgers into the PROCESS-WIDE registry; earlier tests
    # in this pytest process (shardsvc, harness) left counts behind, so
    # each e2e test starts from a cleared registry to assert exact
    # values instead of deltas
    get_registry().reset()
    return JanusService(JanusConfig(
        num_nodes=4, window=8, ops_per_block=16, shards=shards,
        obs_port=0,
        types=(TypeConfig("pnc", {"num_keys": 16}),)))


def _settle(base: str, want_replied: int, timeout: float = 60.0) -> dict:
    deadline = time.time() + timeout
    while time.time() < deadline:
        snap = scrape_json(base + "/slo")
        if snap["replied_total"] >= want_replied:
            return snap
        time.sleep(0.05)
    raise TimeoutError(f"ledger stuck below {want_replied}: {snap}")


def test_unsharded_slo_endpoint_reconciles_with_the_client():
    svc = _mk_service(1)
    port = svc.start()
    assert svc.obs_port > 0
    base = f"http://127.0.0.1:{svc.obs_port}"
    try:
        with JanusClient("127.0.0.1", port, timeout=120) as c:
            for k in KEYS:                              # 4 safe creates
                c.request("pnc", k, "s", timeout=120)
            for i in range(8):                          # 8 unsafe updates
                seq = c.send("pnc", KEYS[i % 4], "i", ["2"])
            c.wait(seq, timeout=120)
            c.request("pnc", "o0", "i", ["1"], is_safe=True,
                      timeout=120)                      # 1 safe update
            c.request("pnc", "o0", "gp", timeout=120)   # 1 unsafe read
            c.request("pnc", "o0", "gs", timeout=120)   # 1 stable read
            snap = _settle(base, want_replied=15)
        cl = snap["classes"]
        assert cl["safe"]["replied"] == 5       # 4 creates + 1 safe inc
        assert cl["unsafe"]["replied"] == 9     # 8 incs + 1 gp
        assert cl["stable"]["replied"] == 1     # 1 gs
        assert snap["replied_total"] == 15
        # every data op was stamped by this client, so every reply
        # sampled a latency
        for c_ in OP_CLASSES:
            assert cl[c_]["e2e_samples"] == cl[c_]["replied"]
            assert cl[c_]["e2e_p99_ms"] >= cl[c_]["e2e_p50_ms"] > 0
        # counter ledger: nothing offered was shed, everything offered
        # was admitted (in-band stats ops from other tests' pattern —
        # none here — would inflate offered, never replied)
        assert snap["shed"] == 0
        assert snap["offered"] == snap["admitted"] >= 15
        # the out-of-band metrics view carries the same instruments
        text = scrape_text(base + "/metrics")
        assert "slo_replied_unsafe_total 9" in text
        assert "slo_e2e_safe_ns_count 5" in text
        health = scrape_json(base + "/health")
        assert health["status"] in ("OK", "DEGRADED", "STALLED")
    finally:
        svc.stop()


def test_sharded_slo_endpoint_merges_per_shard_nodes():
    svc = _mk_service(2)
    port = svc.start()
    base = f"http://127.0.0.1:{svc.obs_port}"
    try:
        with JanusClient("127.0.0.1", port, timeout=120) as c:
            for k in KEYS:
                c.request("pnc", k, "s", timeout=120)
            sender = BatchSender("127.0.0.1", port)
            idx = [i % 4 for i in range(64)]            # spans both shards
            sender.send_frame("pnc", KEYS, idx, "i",
                              p0=[1] * 64)
            snap = _settle(base, want_replied=68)
            sender.close()
            got = int(c.request("pnc", "o0", "gp", timeout=120)["result"])
            assert got == 16
        assert set(snap["nodes"]) == {"s0", "s1"}
        for node in snap["nodes"].values():
            assert node["offered"] == node["admitted"] > 0
        assert snap["classes"]["unsafe"]["replied"] == 64
        # batch-frame t0 rides the v2 header through the shard inbox:
        # every unsafe op sampled a latency on its owning shard
        assert snap["classes"]["unsafe"]["e2e_samples"] == 64
        text = scrape_text(base + "/metrics")
        assert "slo_s0_replied_unsafe_total" in text
        assert "slo_s1_replied_unsafe_total" in text
    finally:
        svc.stop()


def test_v1_batch_frame_applies_but_counts_unstamped():
    """A pre-t0 client's frame (version byte 1, no <q t0_ns after seq0)
    must still apply its ops; the ledger counts them replied with zero
    latency samples."""
    svc = _mk_service(1)
    port = svc.start()
    base = f"http://127.0.0.1:{svc.obs_port}"
    try:
        with JanusClient("127.0.0.1", port, timeout=120) as c:
            c.request("pnc", "o0", "s", timeout=120)
            s0 = scrape_json(base + "/slo")
            before = s0["classes"]["unsafe"]
            tc = b"pnc"
            head = bytearray([0x00, 1, len(tc)])  # magic, VERSION 1
            head.extend(tc)
            head.extend(struct.pack("<I", 1))     # seq0 (no t0 follows)
            head.extend(struct.pack("<H", 1))
            kb = b"o0"
            head.extend(struct.pack("<H", len(kb)))
            head.extend(kb)
            m = 8
            head.extend(struct.pack("<I", m))
            payload = (bytes(head)
                       + np.zeros(m, np.int32).tobytes()
                       + np.full(m, ord("i"), np.uint8).tobytes()
                       + np.zeros(m, np.uint8).tobytes()
                       + np.full(m, 3, np.int64).tobytes())
            s = socket.create_connection(("127.0.0.1", port), timeout=30)
            s.sendall(frame0(payload))
            snap = _settle(base, s0["replied_total"] + m)
            got = int(c.request("pnc", "o0", "gp", timeout=120)["result"])
            assert got == 24
            after = snap["classes"]["unsafe"]
            assert after["replied"] - before["replied"] == m
            assert after["e2e_samples"] == before["e2e_samples"]
            s.close()
    finally:
        svc.stop()


def test_percentile_from_counts_reconciles_with_histogram():
    h = Histogram("_p")
    h.record_many(np.full(100, 5_000, np.int64))
    assert percentile_from_counts(h.counts(), 0.5) == h.percentile(0.5)
    assert percentile_from_counts([], 0.99) == 0.0
    assert percentile_from_counts([0, 0], 0.5) == 0.0
