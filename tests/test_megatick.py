"""Fused multi-type megatick (MultiKV): K consensus rounds for EVERY
registered SafeKV in ONE dispatch must be bit-identical to stepping each
kv's own step_k separately — and must compile exactly once, however many
megaticks run. The dispatch counter is the measured claim: a depth-K
drive of a two-type key space is one host->device round trip per
megatick instead of one per type (or 2K for unfused stepping)."""
import numpy as np
import pytest

from janus_tpu.consensus import DagConfig
from janus_tpu.models import base, orset, pncounter
from janus_tpu.runtime.safecrdt import MultiKV, SafeKV
from janus_tpu.utils.ids import TagMinter

N, W, B, K = 4, 8, 4, 8


def _pnc_kv():
    return SafeKV(DagConfig(N, W), pncounter.SPEC, ops_per_block=B,
                  num_keys=8, num_writers=N)


def _orset_kv():
    return SafeKV(DagConfig(N, W), orset.SPEC, ops_per_block=B,
                  num_keys=8, capacity=32, rm_capacity=4)


def _pnc_ops(rng, k):
    shape = (k, N, B)
    return base.make_op_batch(
        op=rng.integers(pncounter.OP_INC, pncounter.OP_DEC + 1, shape),
        key=rng.integers(0, 8, shape),
        a0=rng.integers(1, 5, shape),
        writer=np.broadcast_to(
            np.arange(N, dtype=np.int32)[None, :, None], shape).copy())


def _orset_ops(rng, k, minters):
    shape = (k, N, B)
    is_add = rng.random(shape) < 0.6
    tags = np.zeros(shape + (2,), np.int32)
    for v in range(N):
        lanes = np.nonzero(is_add[:, v, :].ravel())[0]
        if lanes.size:
            minted = minters[v].mint_many(lanes.size)
            flat = tags[:, v, :, :].reshape(-1, 2)
            flat[lanes] = minted
            tags[:, v, :, :] = flat.reshape(k, B, 2)
    return base.make_op_batch(
        op=np.where(is_add, orset.OP_ADD, orset.OP_REMOVE).astype(np.int32),
        key=rng.integers(0, 8, shape),
        a0=rng.integers(0, 16, shape),
        a1=tags[..., 0], a2=tags[..., 1],
        writer=np.broadcast_to(
            np.arange(N, dtype=np.int32)[None, :, None], shape).copy())


def _device_state_equal(a: SafeKV, b: SafeKV, label: str):
    for name in ("prospective", "stable", "dag", "commit", "ops_buffer"):
        ta, tb = getattr(a, name), getattr(b, name)
        for f in ta:
            np.testing.assert_array_equal(
                np.asarray(ta[f]), np.asarray(tb[f]),
                err_msg=f"{label}: {name}.{f}")


def test_multikv_matches_separate_safekvs():
    """3 megaticks x depth K over {pnc, orset}: device state, host
    observations, commit logs, and stats all bit-identical to the
    separately-stepped kvs."""
    rng_a = np.random.default_rng(3)
    rng_b = np.random.default_rng(3)
    minters_a = [TagMinter(v) for v in range(N)]
    minters_b = [TagMinter(v) for v in range(N)]

    sep = {"pnc": _pnc_kv(), "orset": _orset_kv()}
    fused_kvs = {"pnc": _pnc_kv(), "orset": _orset_kv()}
    multi = MultiKV(fused_kvs)

    sep_infos, fused_infos = [], []
    for _ in range(3):
        ops_k = {"pnc": _pnc_ops(rng_a, K),
                 "orset": _orset_ops(rng_a, K, minters_a)}
        infos = {}
        for tc in ("pnc", "orset"):
            packed_k, metas = sep[tc].step_k_dispatch(ops_k[tc])
            infos[tc] = sep[tc].step_k_absorb(packed_k, metas)
        sep_infos.append(infos)
    for _ in range(3):
        ops_k = {"pnc": _pnc_ops(rng_b, K),
                 "orset": _orset_ops(rng_b, K, minters_b)}
        fused_infos.append(multi.step_k(ops_k))

    for tc in ("pnc", "orset"):
        _device_state_equal(sep[tc], fused_kvs[tc], tc)
        np.testing.assert_array_equal(sep[tc].commit_latencies(),
                                      fused_kvs[tc].commit_latencies())
        assert sep[tc].ordered_commits(0) == fused_kvs[tc].ordered_commits(0)
        assert sep[tc].stats == fused_kvs[tc].stats
    for sa, fa in zip(sep_infos, fused_infos):
        for tc in ("pnc", "orset"):
            assert len(sa[tc]) == len(fa[tc])
            for ia, ib in zip(sa[tc], fa[tc]):
                np.testing.assert_array_equal(ia["accepted"], ib["accepted"])
                np.testing.assert_array_equal(ia["own"], ib["own"])


def test_multikv_one_dispatch_per_k_rounds_and_compiles_once():
    """The perf claim, asserted via counters: >= 3 two-type megaticks
    cost trace_count <= 1 (jax compiled the fused program at most once
    — 0 when an earlier same-geometry MultiKV already populated the
    process-wide shared program cache) and dispatch_count == one per
    megatick — not one per type, not one per round."""
    rng = np.random.default_rng(9)
    minters = [TagMinter(v) for v in range(N)]
    multi = MultiKV({"pnc": _pnc_kv(), "orset": _orset_kv()})
    megaticks = 4
    for _ in range(megaticks):
        multi.step_k({"pnc": _pnc_ops(rng, K),
                      "orset": _orset_ops(rng, K, minters)})
    assert multi.trace_count <= 1
    assert multi.dispatch_count == megaticks
    # every kv really advanced K rounds per megatick
    for kv in multi.kvs.values():
        assert kv.stats["ticks"] == megaticks * K


def test_multikv_rejects_mismatched_geometry():
    other = SafeKV(DagConfig(N, 2 * W), pncounter.SPEC, ops_per_block=B,
                   num_keys=8, num_writers=N)
    with pytest.raises(ValueError, match="geometry"):
        MultiKV({"pnc": _pnc_kv(), "other": other})


def test_multikv_slots_dropped_flows_to_stats():
    """Capacity pressure inside a megatick surfaces through the packed
    slots_dropped scalar into each kv's stats — tiny OR-Set rows plus
    unique minted tags must overflow."""
    kv = SafeKV(DagConfig(N, W), orset.SPEC, ops_per_block=B,
                num_keys=2, capacity=2, rm_capacity=2)
    multi = MultiKV({"orset": kv})
    rng = np.random.default_rng(5)
    minters = [TagMinter(v) for v in range(N)]
    for _ in range(3):
        ops = _orset_ops(rng, K, minters)
        ops["op"] = np.full_like(np.asarray(ops["op"]), orset.OP_ADD)
        ops["key"] = np.asarray(
            rng.integers(0, 2, (K, N, B)), np.int32)
        multi.step_k({"orset": base.make_op_batch(**{
            f: np.asarray(v) for f, v in ops.items()})})
    assert kv.stats["slots_dropped"] > 0
