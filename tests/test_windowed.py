"""Windowed-DAG long-run tests: the ring-buffered round window + GC must
let the cluster tick indefinitely in bounded memory, preserving
convergence and total-order-prefix equality far past the window depth
(reference: DAG.GarbageCollect, DAG.cs:946-965 — rounds committed
everywhere are collected; the reference's 100-round DAGTests :226-271 are
the in-window analog).
"""
import time

import numpy as np

from janus_tpu.consensus import DagConfig, init, init_commit, round_step
from janus_tpu.consensus import commit_view, ordered_blocks
from janus_tpu.models import base, pncounter
from janus_tpu.runtime.safecrdt import SafeKV

N, W, B, K = 4, 8, 4, 8


def pnc_ops(rng):
    shape = (N, B)
    return base.make_op_batch(
        op=rng.integers(pncounter.OP_INC, pncounter.OP_DEC + 1, shape),
        key=rng.integers(0, K, shape),
        a0=rng.integers(1, 5, shape),
        writer=np.broadcast_to(np.arange(N, dtype=np.int32)[:, None], shape),
    )


def make_kv(**kw):
    return SafeKV(DagConfig(N, W), pncounter.SPEC, ops_per_block=B,
                  num_keys=K, num_writers=N, **kw)


def test_runs_ten_windows_with_gc():
    """Tick 10x the window depth under continuous load: the GC frontier
    must advance (bounded memory), every submit past the first window
    must still be accepted, and convergence + total-order prefix
    equality must hold throughout."""
    kv = make_kv()
    rng = np.random.default_rng(3)
    accepted_all = True
    prefix: list = []
    for t in range(10 * W):
        acc = kv.submit(pnc_ops(rng))
        accepted_all = accepted_all and bool(acc.all())
        kv.tick()
        if t % 7 == 0:
            o = kv.ordered_commits(0)
            assert o[: len(prefix)] == prefix
            prefix = o
    assert accepted_all, "window back-pressure fired under steady load"
    assert kv.base_round() > 5 * W, f"GC frontier stalled at {kv.base_round()}"
    # rounds in the log exceed the window depth: the ring really wrapped
    assert max(r for r, _ in kv.ordered_commits(0)) > 3 * W

    for _ in range(6):
        kv.tick()  # drain
    stable = np.asarray(kv.query_stable("get"))
    prosp = np.asarray(kv.query_prospective("get"))
    assert (stable == stable[0]).all()
    np.testing.assert_array_equal(stable, prosp)
    orders = [kv.ordered_commits(v) for v in range(N)]
    shortest = min(len(o) for o in orders)
    assert shortest > 8 * W * N // 2
    for o in orders:
        assert o[:shortest] == orders[0][:shortest]


def test_latency_history_survives_gc():
    kv = make_kv()
    rng = np.random.default_rng(4)
    for _ in range(6 * W):
        kv.submit(pnc_ops(rng), safe=np.ones((N, B), bool))
        kv.tick()
    lats = kv.commit_latencies()
    # nearly every submitted block completed the safe path
    assert lats.size > 4 * W * N
    assert (lats >= 1).all() and np.median(lats) <= W


def test_crash_recovery_state_transfer():
    """A node that stays crashed across several windows is state-
    transferred when it falls behind the GC frontier; after recovery the
    cluster converges and its commit log matches the others — the
    checkpoint/recovery capability the reference lacks (SURVEY §5)."""
    import jax.numpy as jnp

    kv = make_kv()
    rng = np.random.default_rng(5)
    crash = jnp.asarray([True, True, True, False])
    for _ in range(4 * W):
        ops = pnc_ops(rng)
        # crashed node submits nothing
        for f in ops:
            ops[f] = ops[f].at[3].set(0) if hasattr(ops[f], "at") else ops[f]
        kv.submit(ops)
        kv.tick(active=crash)
    assert kv.base_round() > W, "GC must advance past a crashed minority"
    # recovery: full participation again
    for _ in range(3 * W):
        kv.submit(pnc_ops(rng))
        kv.tick()
    stable = np.asarray(kv.query_stable("get"))
    assert (stable == stable[0]).all()
    orders = [kv.ordered_commits(v) for v in range(N)]
    shortest = min(len(o) for o in orders)
    assert shortest > 0
    for o in orders:
        assert o[:shortest] == orders[0][:shortest]


def test_commit_view_trace_scales():
    """VERDICT weak-2 acceptance: the scan-based commit must trace and
    run at production-shaped windows (N=16, W=64) in seconds, where the
    round-1 Python-unrolled version emitted O(N*W^3) ops."""
    cfg = DagConfig(16, 64)
    st = init(cfg)
    cst = init_commit(cfg)
    t0 = time.perf_counter()
    for _ in range(8):
        st = round_step(cfg, st)
    cst = commit_view(cfg, st, cst)
    first = ordered_blocks(cfg, cst, 0)
    dt = time.perf_counter() - t0
    assert len(first) > 0
    assert dt < 120, f"commit_view at N=16,W=64 took {dt:.1f}s"


def test_dag_only_usage_stalls_at_window_edge():
    """Without a GC driver (no commit state), the DAG back-pressures at
    the window edge instead of corrupting slots."""
    cfg = DagConfig(4, 8)
    st = init(cfg)
    for _ in range(20):
        st = round_step(cfg, st)
    assert (np.asarray(st["node_round"]) == cfg.num_rounds - 1).all()


def test_fused_step_matches_submit_tick():
    """The one-dispatch step() path must produce the same states and
    latency bookkeeping as the split submit()+tick() path."""
    kv_a, kv_b = make_kv(), make_kv()
    rng_a, rng_b = np.random.default_rng(9), np.random.default_rng(9)
    safe = np.ones((N, B), bool)
    for _ in range(6 * W):
        acc_a = kv_a.submit(pnc_ops(rng_a), safe=safe)
        kv_a.tick()
        info = kv_b.step(pnc_ops(rng_b), safe=safe)
        np.testing.assert_array_equal(np.asarray(acc_a), info["accepted"])
    sa = np.asarray(kv_a.query_stable("get"))
    sb = np.asarray(kv_b.query_stable("get"))
    np.testing.assert_array_equal(sa, sb)
    pa = np.asarray(kv_a.query_prospective("get"))
    pb = np.asarray(kv_b.query_prospective("get"))
    np.testing.assert_array_equal(pa, pb)
    np.testing.assert_array_equal(kv_a.commit_latencies(),
                                  kv_b.commit_latencies())
    np.testing.assert_array_equal(kv_a.safe_acks(), kv_b.safe_acks())
    for v in range(N):  # collect_logs keeps the total order live on step()
        assert kv_a.ordered_commits(v) == kv_b.ordered_commits(v)
