"""RGA sequence-CRDT tests: semantics against a host-reference model,
convergence under merge, tombstones, depth overflow, compaction, and the
consensus path (BASELINE config 5's type; the reference names the
text-log case but ships no sequence CRDT — paper §6.2 / client stubs
MergeSharp/Examples/KVDB/Client/type/)."""
import numpy as np
import pytest

from janus_tpu.models import base, rga

K, C = 2, 64


def make(n_keys=K, cap=C, depth=16):
    return rga.init(num_keys=n_keys, capacity=cap, max_depth=depth)


def ins(key, ch, parent=(0, 0), writer=0):
    """One insert op batch (host-direct: counter derived in apply)."""
    return base.make_op_batch(
        op=[rga.OP_INSERT], key=[key], a0=[ch],
        a1=[parent[0]], a2=[parent[1]], writer=[writer])


def dele(key, target):
    return base.make_op_batch(
        op=[rga.OP_DELETE], key=[key], a1=[target[0]], a2=[target[1]],
        writer=[0])


def device_text(state, key=0) -> str:
    out = rga.text(state, key)
    chars = np.asarray(out["chr"])
    live = np.asarray(out["live"])
    return "".join(chr(c) for c, m in zip(chars, live) if m)


class HostRGA:
    """Reference model: dict tree + DFS with descending-id siblings."""

    def __init__(self):
        self.elems = {}  # id -> dict(parent, chr, dead)

    def insert(self, eid, parent, ch):
        if eid not in self.elems:
            self.elems[eid] = {"parent": parent, "chr": ch, "dead": False}

    def delete(self, eid):
        if eid in self.elems:
            self.elems[eid]["dead"] = True
        else:  # tombstone placeholder (delete before insert)
            self.elems[eid] = {"parent": (0, 0), "chr": 0, "dead": True}

    def max_ctr(self):
        return max((ctr for ctr, _ in self.elems), default=0)

    def merge(self, other):
        for eid, e in other.elems.items():
            if eid not in self.elems:
                self.elems[eid] = dict(e)
            else:
                mine = self.elems[eid]
                mine["dead"] = mine["dead"] or e["dead"]
                mine["parent"] = max(mine["parent"], e["parent"])
                mine["chr"] = max(mine["chr"], e["chr"])

    def text(self) -> str:
        kids = {}
        for eid, e in self.elems.items():
            kids.setdefault(e["parent"], []).append(eid)
        for lst in kids.values():
            lst.sort(reverse=True)  # descending (ctr, rep)
        out = []

        def dfs(eid):
            e = self.elems.get(eid)
            if e is not None and not e["dead"]:
                out.append(chr(e["chr"]))
            for kid in kids.get(eid, ()):  # descending id
                dfs(kid)

        for top in kids.get((0, 0), ()):
            dfs(top)
        return "".join(out)


def test_sequential_typing_reads_in_order():
    st = make()
    prev = (0, 0)
    for i, ch in enumerate("HELLO"):
        st = rga.apply_ops(st, ins(0, ord(ch), parent=prev, writer=0))
        prev = (0, i + 1)  # parent as (rep, ctr): ids mint ctr=1,2,...
    assert device_text(st) == "HELLO"
    assert int(np.asarray(rga.length(st, 0))) == 5


def test_concurrent_inserts_same_anchor_converge_newest_first():
    # two replicas insert at the head concurrently, then merge both ways
    a, b = make(), make()
    a = rga.apply_ops(a, ins(0, ord("A"), writer=1))  # id (1, 1)
    b = rga.apply_ops(b, ins(0, ord("B"), writer=2))  # id (1, 2)
    ab = rga.merge(a, b)
    ba = rga.merge(b, a)
    # same text both ways; higher id (1,2) comes first (newest-first)
    assert device_text(ab) == device_text(ba) == "BA"


def test_delete_tombstones_but_preserves_descendants():
    st = make()
    st = rga.apply_ops(st, ins(0, ord("X"), writer=0))  # id ctr=1
    st = rga.apply_ops(st, ins(0, ord("Y"), parent=(0, 1), writer=0))
    st = rga.apply_ops(st, dele(0, (0, 1)))  # delete X: target (rep,ctr)
    assert device_text(st) == "Y"
    # the tombstone still occupies a slot (structure for Y)
    assert int(np.asarray(rga.element_count(st))[0]) == 2


def test_delete_before_insert_does_not_resurrect():
    st = make()
    # delete of id (rep=3, ctr=1) replays before its insert
    st = rga.apply_ops(st, dele(0, (3, 1)))
    one = base.make_op_batch(op=[rga.OP_INSERT], key=[0], a0=[ord("Z")],
                             a1=[0], a2=[0], writer=[3])
    prepared = {**one, "eff_ctr": np.asarray([[1]], np.int32)}
    st = rga.apply_ops(st, prepared)
    assert device_text(st) == ""


def test_random_traces_match_host_reference():
    """Property test: random concurrent insert/delete traces with random
    pairwise merges — the device text must equal the host model's."""
    rng = np.random.default_rng(11)
    R = 3
    states = [make(n_keys=1, cap=128, depth=24) for _ in range(R)]
    hosts = [HostRGA() for _ in range(R)]
    for step in range(60):
        r = int(rng.integers(R))
        h, st = hosts[r], states[r]
        observed = [eid for eid, e in h.elems.items() if e["chr"] > 0]
        if observed and rng.random() < 0.2:
            tgt = observed[int(rng.integers(len(observed)))]
            states[r] = rga.apply_ops(st, dele(0, (tgt[1], tgt[0])))
            h.delete(tgt)
        else:
            parent = ((0, 0) if not observed or rng.random() < 0.3
                      else observed[int(rng.integers(len(observed)))])
            ch = ord("a") + int(rng.integers(26))
            ctr = h.max_ctr() + 1
            states[r] = rga.apply_ops(
                st, ins(0, ch, parent=(parent[1], parent[0]), writer=r))
            h.insert((ctr, r), parent, ch)
        if rng.random() < 0.3:
            j = int(rng.integers(R))
            states[r] = rga.merge(states[r], states[j])
            states[j] = rga.merge(states[j], states[r])
            hosts[r].merge(hosts[j])
            hosts[j].merge(hosts[r])
    # full convergence
    for j in range(R):
        states[0] = rga.merge(states[0], states[j])
        hosts[0].merge(hosts[j])
    got = device_text(states[0])
    want = hosts[0].text()
    assert got == want, f"{got!r} != {want!r}"


def test_depth_overflow_flag():
    st = make(depth=4)
    prev = (0, 0)
    for i in range(6):  # chain deeper than max_depth
        st = rga.apply_ops(st, ins(0, ord("a") + i, parent=prev, writer=0))
        prev = (0, i + 1)
    out = rga.text(st, 0)
    assert bool(np.asarray(out["overflow"]))
    shallow = make(depth=8)
    shallow = rga.apply_ops(shallow, ins(0, ord("x"), writer=0))
    assert not bool(np.asarray(rga.text(shallow, 0)["overflow"]))


def test_compact_reclaims_dead_leaves_only():
    st = make()
    st = rga.apply_ops(st, ins(0, ord("X"), writer=0))                # ctr 1
    st = rga.apply_ops(st, ins(0, ord("Y"), parent=(0, 1), writer=0))  # ctr 2
    st = rga.apply_ops(st, ins(0, ord("Z"), parent=(0, 2), writer=0))  # ctr 3
    st = rga.apply_ops(st, dele(0, (0, 1)))  # X: interior tombstone
    st = rga.apply_ops(st, dele(0, (0, 3)))  # Z: leaf tombstone
    before = device_text(st)
    st = rga.compact(st)
    assert device_text(st) == before == "Y"
    # Z's slot reclaimed, X kept (it anchors Y)
    assert int(np.asarray(rga.element_count(st))[0]) == 2


def test_rga_through_consensus():
    """Full SafeKV path: inserts with effect-captured Lamport counters
    ride blocks; stable == prospective and every node reads one text."""
    import jax.numpy as jnp

    from janus_tpu.consensus import DagConfig
    from janus_tpu.runtime.safecrdt import SafeKV

    N, W, B = 4, 8, 2
    kv = SafeKV(DagConfig(N, W), rga.SPEC, ops_per_block=B,
                num_keys=1, capacity=64, max_depth=16)
    # each node types its own letter at the head, concurrently
    op = np.zeros((N, B), np.int32)
    a0 = np.zeros((N, B), np.int32)
    writer = np.broadcast_to(np.arange(N, dtype=np.int32)[:, None], (N, B))
    op[:, 0] = rga.OP_INSERT
    for v in range(N):
        a0[v, 0] = ord("A") + v
    kv.submit(base.make_op_batch(op=op, key=np.zeros((N, B), np.int32),
                                 a0=a0, writer=writer.copy()))
    for _ in range(2 * W):
        kv.tick()
    texts = set()
    for v in range(N):
        out_p = rga.text({f: np.asarray(x[v]) if hasattr(x, "__getitem__")
                          else x for f, x in kv.prospective.items()}, 0)
        out_s = rga.text({f: np.asarray(x[v]) if hasattr(x, "__getitem__")
                          else x for f, x in kv.stable.items()}, 0)
        tp = "".join(chr(c) for c, m in
                     zip(np.asarray(out_p["chr"]), np.asarray(out_p["live"])) if m)
        ts = "".join(chr(c) for c, m in
                     zip(np.asarray(out_s["chr"]), np.asarray(out_s["live"])) if m)
        assert tp == ts
        texts.add(tp)
    assert len(texts) == 1
    assert sorted(texts.pop()) == ["A", "B", "C", "D"]
