"""Replicated-store tests: the analog of the reference's replication suite
(MergeSharp.Tests/ReplicationManagerTests.cs with DummyConnectionManager —
N replicas in one process, ops interleaved, convergence asserted)."""
import jax
import numpy as np

from janus_tpu.models import base, orset, pncounter
from janus_tpu.runtime import store as rs
from janus_tpu.utils.ids import Interner, TagMinter


def test_pnc_replicas_converge_bitwise():
    R, K = 8, 16
    st = rs.replicated_init(pncounter.SPEC, R, num_keys=K, num_writers=R)
    # each replica increments its own key by (replica+1), in its own lane
    ops = base.make_op_batch(
        op=[[pncounter.OP_INC]] * R,
        key=[[r] for r in range(R)],
        a0=[[r + 1] for r in range(R)],
        writer=[[r] for r in range(R)],
    )
    st = rs.apply_replica_ops(pncounter.SPEC, st, ops)
    st = rs.converge(pncounter.SPEC, st)
    vals = np.asarray(jax.vmap(pncounter.value)(st))  # [R, K]
    for r in range(R):
        np.testing.assert_array_equal(vals[r], vals[0])
    np.testing.assert_array_equal(vals[0][:R], np.arange(1, R + 1))
    # bit-equal replicas (canonical form)
    for f, arr in st.items():
        a = np.asarray(arr)
        assert (a == a[0]).all(), f


def test_partial_gossip_ring_distance_one():
    R, K = 4, 4
    st = rs.replicated_init(pncounter.SPEC, R, num_keys=K, num_writers=R)
    ops = base.make_op_batch(
        op=[[1]] * R, key=[[r] for r in range(R)],
        a0=[[10]] * R, writer=[[r] for r in range(R)],
    )
    st = rs.apply_replica_ops(pncounter.SPEC, st, ops)
    st = rs.gossip_step(pncounter.SPEC, st, 1)
    vals = np.asarray(jax.vmap(pncounter.value)(st))
    # replica r saw its own update and replica r-1's, nothing else
    for r in range(R):
        expect = np.zeros(K)
        expect[r] = 10
        expect[(r - 1) % R] = 10
        np.testing.assert_array_equal(vals[r], expect)


def test_orset_store_end_to_end_with_tags():
    R = 4
    s = rs.Store(R, {"orset": {"num_keys": 8, "capacity": 16}})
    elems = Interner()
    minters = [TagMinter(r) for r in range(R)]
    e = elems.intern("apple")
    # every replica adds "apple" to key 2 with its own fresh tag
    tags = np.stack([m.mint_many(1)[0] for m in minters])  # [R, 2]
    ops = base.make_op_batch(
        op=[[orset.OP_ADD]] * R, key=[[2]] * R,
        a0=[[e]] * R, a1=tags[:, :1].tolist(), a2=tags[:, 1:].tolist(),
    )
    s.apply("orset", ops)
    # replica 0 removes before seeing others' adds -> add-wins after sync
    s.apply("orset", base.make_op_batch(
        op=[[orset.OP_REMOVE]] + [[base.OP_NOOP]] * (R - 1),
        key=[[2]] * R, a0=[[e]] * R,
    ))
    s.sync("orset")
    present = np.asarray(s.query("orset", "contains", 2, e))
    assert present.all()  # other replicas' tags survive replica 0's remove
    counts = np.asarray(s.query("orset", "live_count"))
    assert (counts[:, 2] == R - 1).all()


def test_store_join_all_matches_any_replica():
    R = 5  # non-power-of-two ring
    st = rs.replicated_init(pncounter.SPEC, R, num_keys=4, num_writers=R)
    ops = base.make_op_batch(
        op=[[1]] * R, key=[[r % 4] for r in range(R)],
        a0=[[1]] * R, writer=[[r] for r in range(R)],
    )
    st = rs.apply_replica_ops(pncounter.SPEC, st, ops)
    joined = rs.join_all(pncounter.SPEC, st)
    vals = np.asarray(pncounter.value(joined))
    assert vals.sum() == R


def test_interner_roundtrip():
    it = Interner()
    a = it.intern("x")
    assert it.intern("x") == a and "x" in it
    assert it.lookup(a) == "x"
    b = it.intern(("composite", 3))
    assert b == 1 and len(it) == 2
