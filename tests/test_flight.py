"""Flight recorder + causal trace tests (PR 3 tentpole).

The recorder's contract: O(1) append into a preallocated ring — wrap
keeps the NEWEST events and the buffer object never reallocates — and
trace ids threaded through ``SafeKV.step`` land every pipeline leg
(ingest -> seal -> dag_round -> commit -> apply) under one id, in a
Perfetto-loadable Chrome trace export.
"""
import json
import time

import numpy as np

from janus_tpu.obs import flight
from janus_tpu.obs.flight import FlightRecorder
from janus_tpu.obs.traceview import (
    chrome_trace_json,
    span_chains,
    write_chrome_trace,
)

CHAIN = {"ingest", "seal", "dag_round", "commit", "apply"}


def test_ring_wraparound_keeps_newest_never_reallocs():
    rec = FlightRecorder(capacity=8)
    buf_id = id(rec._buf)
    for i in range(20):
        rec.event(f"t{i}", "mark", "I", detail=i)
    # the ring never grew and never swapped buffers
    assert id(rec._buf) == buf_id
    assert len(rec._buf) == 8
    assert rec.total == 20
    snap = rec.snapshot()
    assert len(snap) == 8
    # the 8 NEWEST events survive, returned oldest-first
    assert [e[4] for e in snap] == list(range(12, 20))


def test_span_context_manager_records_complete_span():
    rec = FlightRecorder(capacity=4)
    with rec.span("c1", "work"):
        pass
    (_t0, tid, span, kind, dur) = rec.snapshot()[0]
    assert (tid, span, kind) == ("c1", "work", "S")
    assert dur >= 0


def test_disabled_recorder_records_nothing():
    rec = FlightRecorder(capacity=4, enabled=False)
    rec.event("x", "y")
    rec.span_at("x", "y", 0, 5)
    assert rec.total == 0
    assert rec.snapshot() == []


def test_dump_writes_json_lines(tmp_path):
    rec = FlightRecorder(capacity=4)
    rec.event("a", "m", "I", detail="d")
    p = tmp_path / "f.jsonl"
    assert rec.dump(str(p)) == 1
    row = json.loads(p.read_text())
    assert row["trace_id"] == "a"
    assert row["span"] == "m"


def test_chrome_trace_export_shape():
    rec = FlightRecorder(capacity=16)
    rec.span_at("c1", "seal", 1_000_000, 2_000_000)
    rec.event("c1", "recycled", "I", detail="slot=3")
    doc = json.loads(chrome_trace_json(rec.snapshot()))
    evs = doc["traceEvents"]
    metas = [e for e in evs if e["ph"] == "M"]
    xs = [e for e in evs if e["ph"] == "X"]
    insts = [e for e in evs if e["ph"] == "i"]
    assert metas[0]["name"] == "thread_name"
    assert metas[0]["args"]["name"] == "c1"
    assert xs[0]["name"] == "seal"
    assert xs[0]["ts"] == 1000.0 and xs[0]["dur"] == 1000.0  # us
    assert insts[0]["name"] == "recycled"
    assert insts[0]["args"]["detail"] == "slot=3"


def test_causal_chain_through_safekv(tmp_path):
    """A traced safe update's block shows the FULL pipeline chain —
    ingest -> seal -> dag_round -> commit -> apply — under one trace id,
    and the Perfetto export carries it."""
    from janus_tpu.consensus import DagConfig
    from janus_tpu.models import base, pncounter
    from janus_tpu.runtime.safecrdt import SafeKV

    rec = flight.enable()
    rec.clear()
    try:
        n, B = 4, 8
        kv = SafeKV(DagConfig(n, 8), pncounter.SPEC, ops_per_block=B,
                    collect_logs=False, num_keys=16, num_writers=n)
        rng = np.random.default_rng(0)
        writer = np.broadcast_to(
            np.arange(n, dtype=np.int32)[:, None], (n, B)).copy()
        safe = np.ones((n, B), bool)
        for t in range(40):
            ops = base.make_op_batch(
                op=np.full((n, B), pncounter.OP_INC, np.int32),
                key=rng.integers(0, 16, (n, B)).astype(np.int32),
                a0=np.ones((n, B), np.int32), writer=writer)
            trace = [f"n{v}.t{t}" for v in range(n)]
            t0 = time.time_ns()
            for tid in trace:
                rec.span_at(tid, "ingest", t0, time.time_ns())
            kv.step(ops, safe=safe, record=True, trace=trace)
    finally:
        flight.disable()

    chains = span_chains(rec.snapshot())
    full = [tid for tid, spans in chains.items() if CHAIN <= set(spans)]
    assert full, (
        f"no complete causal chain among {len(chains)} traces; "
        f"example chains: {dict(list(chains.items())[:4])}")
    # the chain is causally ordered: ingest first, apply last
    spans = chains[full[0]]
    assert spans[0] == "ingest"
    assert spans.index("seal") < spans.index("commit") < spans.index("apply")

    out = tmp_path / "trace.json"
    n_ev = write_chrome_trace(str(out), rec)
    assert n_ev > 0
    doc = json.loads(out.read_text())
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert CHAIN <= names
