"""Telemetry-plane tests: bucket math, percentile accuracy bounds,
exposition round-trips, and the `metrics` service command over real TCP.

The histogram contract under test: 64 fixed power-of-two buckets,
bucket 0 = {<=0}, bucket i = [2^(i-1), 2^i), values >= 2^62 land in the
overflow bucket; percentiles interpolate within one bucket, so the
estimate is bounded by the true value's bucket edges — at most 2x off
in either direction.
"""
import json

import numpy as np
import pytest

from janus_tpu.obs.export import (
    parse_prometheus,
    render_prometheus,
    snapshot_json,
)
from janus_tpu.obs.metrics import (
    BUCKET_HI,
    BUCKET_LO,
    NUM_BUCKETS,
    Histogram,
    Registry,
    bucket_index,
)


# -- bucket math ---------------------------------------------------------

def test_bucket_index_edges():
    assert bucket_index(0) == 0
    assert bucket_index(-5) == 0
    assert bucket_index(1) == 1
    for k in range(2, 40):
        # 2^(k-1) opens bucket k; 2^k - 1 closes it
        assert bucket_index(1 << (k - 1)) == k
        assert bucket_index((1 << k) - 1) == k
    # edges agree with the published bucket ranges
    for k in range(1, 40):
        i = bucket_index(1 << (k - 1))
        assert BUCKET_LO[i] <= (1 << (k - 1)) < BUCKET_HI[i]


def test_bucket_index_overflow_clips():
    last = NUM_BUCKETS - 1
    assert bucket_index(1 << 62) == last
    assert bucket_index(1 << 200) == last
    h = Histogram("t")
    h.record(1 << 100)
    h.record((1 << 62) + 7)
    assert h.counts()[last] == 2
    assert h.count == 2


def test_histogram_negative_and_zero_to_bucket_zero():
    h = Histogram("t")
    h.record(0)
    h.record(-123)
    assert h.counts()[0] == 2
    assert h.sum == 0  # negatives clamp to 0, not to garbage


def test_histogram_single_value_percentile_exact_bucket():
    h = Histogram("t")
    for _ in range(100):
        h.record(1000)
    # 1000 lives in [512, 1024); any percentile must stay in-bucket
    for q in (0.0, 0.5, 0.99, 1.0):
        assert 512 <= h.percentile(q) <= 1024


def test_percentiles_vs_numpy_within_bucket_bounds():
    rng = np.random.default_rng(7)
    vals = np.maximum(1, rng.lognormal(12, 2.0, size=5000).astype(np.int64))
    h = Histogram("t")
    for v in vals:
        h.record(int(v))
    for q in (0.5, 0.9, 0.99):
        est = h.percentile(q)
        true = float(np.percentile(vals, 100 * q))
        # power-of-two buckets: estimate and truth share a bucket (or
        # straddle one edge), so the ratio is bounded by one octave
        assert 0.5 <= est / true <= 2.0, (q, est, true)


def test_histogram_record_seconds_is_nanoseconds():
    h = Histogram("t")
    h.record_seconds(0.001)
    assert h.sum == pytest.approx(1_000_000, rel=0.01)


# -- registry ------------------------------------------------------------

def test_registry_type_conflict_raises():
    reg = Registry()
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.histogram("x")


def test_registry_disabled_hands_out_noops():
    reg = Registry(enabled=False)
    reg.counter("c").add(5)
    reg.histogram("h").record(123)
    assert reg.names() == []  # nothing registered, nothing exported


# -- exposition ----------------------------------------------------------

def _populated_registry():
    reg = Registry()
    reg.counter("ops_total").add(42)
    reg.gauge("block_size").set(256)
    h = reg.histogram("stage_test_commit_ns")
    for v in (100, 1000, 1000, 50_000_000):
        h.record(v)
    return reg


def test_prometheus_render_parse_roundtrip():
    reg = _populated_registry()
    parsed = parse_prometheus(render_prometheus(reg))
    assert parsed["ops_total"] == 42
    assert parsed["block_size"] == 256
    hist = parsed["stage_test_commit_ns"]
    assert hist["count"] == 4
    assert hist["sum"] == 100 + 1000 + 1000 + 50_000_000
    # cumulative buckets are monotone and end at count
    cums = [hist["buckets"][le] for le in sorted(
        hist["buckets"], key=float)]
    assert cums == sorted(cums)
    assert cums[-1] == 4


def test_prometheus_histogram_cumulative_buckets():
    reg = Registry()
    h = reg.histogram("h_ns")
    h.record(3)    # bucket le=4
    h.record(100)  # bucket le=128
    text = render_prometheus(reg)
    assert 'h_ns_bucket{le="4"} 1' in text
    assert 'h_ns_bucket{le="128"} 2' in text
    assert 'h_ns_bucket{le="+Inf"} 2' in text
    assert "h_ns_count 2" in text


def test_prometheus_help_lines_precede_type():
    reg = _populated_registry()
    text = render_prometheus(reg)
    lines = text.splitlines()
    for name in ("ops_total", "block_size", "stage_test_commit_ns"):
        hi = next(i for i, ln in enumerate(lines)
                  if ln.startswith(f"# HELP {name} "))
        ti = next(i for i, ln in enumerate(lines)
                  if ln.startswith(f"# TYPE {name} "))
        assert hi < ti
    # the parser skips HELP comments and still round-trips
    assert parse_prometheus(text)["ops_total"] == 42


def test_prometheus_emits_zero_count_bucket_edges():
    reg = Registry()
    h = reg.histogram("h_ns")
    h.record(0)     # bucket 0, edge le=1
    h.record(100)   # bucket 7, edge le=128
    text = render_prometheus(reg)
    # EVERY edge up to the max observed bucket appears — including the
    # zero-count ones in between, because scrape clients interpolate
    # between ADJACENT emitted edges and a missing edge fakes precision
    for i in range(8):
        want = 1 if i < 7 else 2
        assert f'h_ns_bucket{{le="{BUCKET_HI[i]}"}} {want}' in text
    # ...and nothing beyond the observed range except +Inf
    assert 'le="256"' not in text
    assert 'h_ns_bucket{le="+Inf"} 2' in text


def test_snapshot_json_shape():
    reg = _populated_registry()
    doc = json.loads(snapshot_json(reg))["metrics"]
    assert doc["ops_total"]["value"] == 42
    assert doc["stage_test_commit_ns"]["count"] == 4
    assert doc["stage_test_commit_ns"]["p50"] > 0


# -- metrics command over real TCP --------------------------------------

@pytest.fixture(scope="module")
def service():
    from janus_tpu.net import JanusConfig, JanusService, TypeConfig

    cfg = JanusConfig(
        num_nodes=4, window=8, ops_per_block=8,
        types=(TypeConfig("pnc", {"num_keys": 16}),),
    )
    svc = JanusService(cfg)
    port = svc.start()
    yield svc, port
    svc.stop()


def test_metrics_command_round_trip(service):
    from janus_tpu.net import JanusClient

    svc, port = service
    with JanusClient("127.0.0.1", port, timeout=60) as c:
        c.request("pnc", "k", "s")
        c.request("pnc", "k", "i", ["5"])
        c.request("pnc", "k", "d", ["1"], is_safe=True)

        scraped = c.scrape(timeout=60)
        # measured stage histograms, not derived numbers
        commit = scraped["stage_pnc_commit_ns"]
        assert commit["count"] >= 1
        assert commit["sum"] > 0
        assert scraped["stage_svc_ingest_ns"]["count"] >= 1
        # DAG/commit gauges come from the consensus state itself
        assert scraped["dag_pnc_node_round_min"] >= 1
        assert scraped["svc_pnc_block_size"] == 8

        # the JSON side rides the existing stats command
        st = c.stats(timeout=60)
        assert st["metrics"]["stage_pnc_commit_ns"]["count"] >= 1


# -- per-shard service-plane instruments --------------------------------

def test_shard_instruments_create_and_record():
    from janus_tpu.obs.metrics import shard_instruments

    reg = Registry()
    ins = {k: shard_instruments(k, reg) for k in range(2)}
    ins[0]["ops_total"].add(4096)
    ins[0]["queue_depth"].set(17)
    ins[1]["step_lag"].set(2.5)
    snap = reg.snapshot()
    assert snap["shard0_ops_total"] == {"type": "counter", "value": 4096}
    assert snap["shard0_queue_depth"]["value"] == 17
    assert snap["shard1_step_lag_ms"]["value"] == 2.5
    # idempotent: asking again hands back the SAME instruments (the
    # worker re-resolves on restart without double-registering)
    again = shard_instruments(0, reg)
    assert again["ops_total"] is ins[0]["ops_total"]


def test_shard_instruments_render_with_help_lines():
    from janus_tpu.obs.metrics import shard_instruments

    reg = Registry()
    ins = shard_instruments(3, reg)
    ins["ops_total"].add(7)
    ins["queue_depth"].set(1)
    ins["step_lag"].set(0.25)
    text = render_prometheus(reg)
    lines = text.splitlines()
    for name in ("shard3_ops_total", "shard3_queue_depth",
                 "shard3_step_lag_ms"):
        hi = next(i for i, ln in enumerate(lines)
                  if ln.startswith(f"# HELP {name} "))
        ti = next(i for i, ln in enumerate(lines)
                  if ln.startswith(f"# TYPE {name} "))
        assert hi < ti
    parsed = parse_prometheus(text)
    assert parsed["shard3_ops_total"] == 7
    assert parsed["shard3_queue_depth"] == 1
