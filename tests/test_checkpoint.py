"""Checkpoint/resume tests: a restored cluster continues bit-identically
with the original (a capability the reference lacks — SURVEY §5:
'Checkpoint/resume: None — no persistence anywhere')."""
import numpy as np

from janus_tpu.consensus import DagConfig
from janus_tpu.models import base, pncounter
from janus_tpu.runtime.safecrdt import SafeKV
from janus_tpu.utils.trace import Tracer

N, W, B, K = 4, 8, 4, 8


def pnc_ops(rng):
    shape = (N, B)
    return base.make_op_batch(
        op=rng.integers(pncounter.OP_INC, pncounter.OP_DEC + 1, shape),
        key=rng.integers(0, K, shape),
        a0=rng.integers(1, 5, shape),
        writer=np.broadcast_to(np.arange(N, dtype=np.int32)[:, None], shape))


def make_kv():
    return SafeKV(DagConfig(N, W), pncounter.SPEC, ops_per_block=B,
                  num_keys=K, num_writers=N)


def test_checkpoint_resume_continues_identically(tmp_path):
    path = str(tmp_path / "ckpt.npz")
    rng_a, rng_b = np.random.default_rng(21), np.random.default_rng(21)
    kv_a, kv_b = make_kv(), make_kv()
    safe = np.ones((N, B), bool)
    for _ in range(2 * W):  # shared prefix
        kv_a.step(pnc_ops(rng_a), safe=safe)
        kv_b.step(pnc_ops(rng_b), safe=safe)
    kv_a.checkpoint(path)

    # restart: a FRESH instance restores mid-run and continues
    kv_r = make_kv()
    kv_r.restore(path)
    for _ in range(2 * W):
        ops = pnc_ops(rng_a)
        kv_r.step(ops, safe=safe)
        kv_b.step(pnc_ops(rng_b), safe=safe)
    np.testing.assert_array_equal(
        np.asarray(kv_r.query_stable("get")),
        np.asarray(kv_b.query_stable("get")))
    np.testing.assert_array_equal(
        np.asarray(kv_r.query_prospective("get")),
        np.asarray(kv_b.query_prospective("get")))
    assert kv_r.tick_count == kv_b.tick_count
    np.testing.assert_array_equal(kv_r.commit_latencies(),
                                  kv_b.commit_latencies())
    for v in range(N):
        assert kv_r.ordered_commits(v) == kv_b.ordered_commits(v)


def test_tracer_spans():
    tr = Tracer()
    with tr.span("work"):
        sum(range(1000))
    with tr.span("work"):
        sum(range(1000))
    rep = tr.report()
    assert rep["work"]["count"] == 2
    assert rep["work"]["total_ms"] >= 0
