"""Replicated-keyspace tests: creates ride DAG blocks, views materialize
key->slot tables in committed total order (reference:
KeySpaceManager.cs:55-113 primary-create + :151-177 remote
auto-materialization, recast as commit-order slot assignment)."""
import numpy as np

from janus_tpu.consensus import DagConfig
from janus_tpu.models import base, pncounter
from janus_tpu.runtime.keyspace import KeySpace, ReplicatedKeySpace
from janus_tpu.runtime.safecrdt import SafeKV

N, W, B, K = 4, 8, 4, 8


def make_kv():
    return SafeKV(DagConfig(N, W), pncounter.SPEC, ops_per_block=B,
                  num_keys=K, num_writers=N)


def idle_ops():
    return base.make_op_batch(op=np.zeros((N, B), np.int32))


def test_create_visible_only_after_commit_and_tables_identical():
    kv = make_kv()
    rks = ReplicatedKeySpace(N, K)
    # node 0 creates "alpha" riding its next block
    info = kv.step(idle_ops())
    rks.register_create(0, "alpha", int(info["round"][0]))
    rks.advance(kv)
    # not yet committed anywhere — node 3 (and even node 0) cannot see it
    assert rks.slot(3, "alpha") is None
    assert rks.slot(0, "alpha") is None
    for _ in range(2 * W):
        kv.step(idle_ops())
        rks.advance(kv)
    assert rks.slot(0, "alpha") == 0
    assert rks.slot(3, "alpha") == 0
    assert rks.consistent_prefix()


def test_concurrent_creates_get_identical_slot_order():
    kv = make_kv()
    rks = ReplicatedKeySpace(N, K)
    info = kv.step(idle_ops())
    # all four nodes create distinct keys in the same round
    for v in range(N):
        rks.register_create(v, f"k{v}", int(info["round"][v]))
    for _ in range(3 * W):
        kv.step(idle_ops())
        rks.advance(kv)
    # every view assigned the same slots (total-order tie-break by source)
    assert rks.consistent_prefix()
    tables = rks.tables
    assert all(t == tables[0] for t in tables)
    assert sorted(tables[0].values()) == [0, 1, 2, 3]


def test_duplicate_creates_collapse_to_first_committed():
    kv = make_kv()
    rks = ReplicatedKeySpace(N, K)
    info = kv.step(idle_ops())
    rks.register_create(1, "dup", int(info["round"][1]))
    rks.register_create(2, "dup", int(info["round"][2]))
    for _ in range(3 * W):
        kv.step(idle_ops())
        rks.advance(kv)
    assert all(t.get("dup") == 0 and len(t) == 1 for t in rks.tables)


def test_plain_keyspace_resolve():
    ks = KeySpace({"pnc": 4})
    s0, existed = ks.resolve("pnc", "a")
    assert not existed and s0 == 0
    s1, existed = ks.resolve("pnc", "a")
    assert existed and s1 == 0


# -- shard_of: the sharded service plane's routing hash -----------------

def test_shard_of_stable_across_restarts():
    """shard_of is FNV-1a over "{type}/{key}" — no process state, no
    PYTHONHASHSEED. These values are pinned: a drift here silently
    re-homes every key after a restart (a reconnecting client would
    stop finding its data)."""
    from janus_tpu.runtime.keyspace import shard_of

    assert [shard_of("pnc", f"o{k}", 2) for k in range(8)] == \
        [0, 1, 0, 1, 0, 1, 0, 1]
    assert [shard_of("pnc", f"o{k}", 4) for k in range(8)] == \
        [2, 1, 0, 3, 2, 1, 0, 3]
    assert shard_of("orset", "o0", 4) == 2      # type code is hashed too
    assert shard_of("pnc", "user:42", 7) == 3


def test_shard_of_uniform_over_keyspace():
    """Over 10k distinct keys every shard holds its fair share +/- 20%
    — the load-balance property the per-shard megatick relies on."""
    from janus_tpu.runtime.keyspace import shard_of

    for ns in (2, 4, 8):
        counts = [0] * ns
        for k in range(10_000):
            counts[shard_of("pnc", f"key-{k}", ns)] += 1
        fair = 10_000 / ns
        for c in counts:
            assert 0.8 * fair <= c <= 1.2 * fair, (ns, counts)


def test_shard_of_degenerate_single_shard():
    from janus_tpu.runtime.keyspace import shard_of

    assert all(shard_of("pnc", f"k{i}", 1) == 0 for i in range(64))
    assert shard_of("pnc", "k", 0) == 0  # guard, not a divide
