"""DAG message plane + split-cluster transport tests.

Reference analogs: serialization round-trip + subtype demux
(Tests/DAGBlockAndMsgTests.cs:46-170), raw-TCP message exchange
(TestMessagesOverTCP :170), and multi-endpoint DAG runs converging over
a real transport (Tests/DAGServerTests.cs:13-201 — 4 ManagerServers on
loopback reach >=50 rounds with identical ordered prefixes)."""
import socket
import time

import numpy as np

from janus_tpu.consensus import DagConfig, commit_view, init_commit, ordered_blocks
from janus_tpu.net.dagplane import (
    MSG_BLOCK,
    MSG_CERT,
    MSG_SIG,
    SplitClusterEndpoint,
    TcpPeer,
    decode_messages,
    encode_block,
    encode_certificate,
    encode_signature,
)

N, W = 4, 8


def test_message_roundtrip_and_demux():
    edges = np.asarray([True, False, True, True])
    buf = bytearray()
    buf += encode_block(12, 3, edges)
    buf += encode_signature(12, 3, 1)
    buf += encode_certificate(12, 3)
    msgs = decode_messages(buf)
    assert [m for m, _ in msgs] == [MSG_BLOCK, MSG_SIG, MSG_CERT]
    assert msgs[0][1]["round"] == 12 and msgs[0][1]["source"] == 3
    np.testing.assert_array_equal(msgs[0][1]["edges"], edges)
    assert msgs[1][1]["signer"] == 1
    assert len(buf) == 0  # fully drained


def test_partial_frame_waits_for_more_bytes():
    whole = encode_block(2, 0, np.ones(N, bool))
    buf = bytearray(whole[: len(whole) // 2])
    assert decode_messages(buf) == []
    buf += whole[len(whole) // 2:]
    assert len(decode_messages(buf)) == 1


def _run_split(cfg, link_a_to_b, link_b_to_a):
    a = SplitClusterEndpoint(cfg, np.asarray([True, True, False, False]),
                             send=link_a_to_b)
    b = SplitClusterEndpoint(cfg, np.asarray([False, False, True, True]),
                             send=link_b_to_a)
    return a, b


def test_split_cluster_converges_in_memory():
    """Two endpoints, each owning half the nodes, exchange DAG messages
    and advance in lockstep; both sides commit the same total-order
    prefix (the DAGServerTests liveness+agreement check)."""
    cfg = DagConfig(N, W)
    inbox_a, inbox_b = [], []
    a, b = _run_split(cfg, inbox_b.append, inbox_a.append)
    commits_a, commits_b = init_commit(cfg), init_commit(cfg)
    # a round needs ~3 message exchanges (block -> sig -> cert), so give
    # the lockstep loop enough iterations to fill the window
    for _ in range(5 * W):
        a.step()
        b.step()
        # flush links both ways (synchronous delivery)
        for data in inbox_a:
            a.receive(data)
        for data in inbox_b:
            b.receive(data)
        inbox_a.clear()
        inbox_b.clear()
    # one more exchange so both sides hold the final messages
    a.step(); b.step()
    # all owned nodes advanced well past genesis (window-bounded)
    assert a.node_rounds().min() >= W - 2
    assert b.node_rounds().min() >= W - 2
    # commit on each side's state: identical ordered prefix
    commits_a = commit_view(cfg, a.state, commits_a)
    commits_b = commit_view(cfg, b.state, commits_b)
    oa = ordered_blocks(cfg, commits_a, 0)
    ob = ordered_blocks(cfg, commits_b, 2)
    shortest = min(len(oa), len(ob))
    assert shortest > 0
    assert oa[:shortest] == ob[:shortest]


def test_split_cluster_over_loopback_tcp():
    """The same exchange over a real TCP socket pair (the
    TestMessagesOverTCP / DAGServerTests shape)."""
    cfg = DagConfig(N, W)
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]

    a = SplitClusterEndpoint(cfg, np.asarray([True, True, False, False]))
    b = SplitClusterEndpoint(cfg, np.asarray([False, False, True, True]))

    peer_b = None
    client = socket.create_connection(("127.0.0.1", port), timeout=10)
    server_side, _ = lsock.accept()
    peer_a = TcpPeer(client, a.receive)
    peer_b = TcpPeer(server_side, b.receive)
    a.send = peer_a.send
    b.send = peer_b.send
    try:
        for _ in range(2 * W):
            a.step()
            b.step()
            time.sleep(0.02)  # let the rx threads drain
        a.step()
        b.step()
        assert a.node_rounds().min() >= W - 2
        assert b.node_rounds().min() >= W - 2
        ca = commit_view(cfg, a.state, init_commit(cfg))
        cb = commit_view(cfg, b.state, init_commit(cfg))
        oa = ordered_blocks(cfg, ca, 0)
        ob = ordered_blocks(cfg, cb, 2)
        shortest = min(len(oa), len(ob))
        assert shortest > 0
        assert oa[:shortest] == ob[:shortest]
    finally:
        peer_a.close()
        peer_b.close()
        lsock.close()
