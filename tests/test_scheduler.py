"""AIMD block-size controller tests, plus SafeKV.resize_block actuation.

The controller's contract: under a trickle with slow seals it walks B
down to the floor; under saturation it walks B up to the ceiling; it
never exceeds the ring-window back-pressure bound max_inflight_ops // W;
and targets quantize so XLA sees a handful of shapes, not one per
adjustment.
"""
import numpy as np
import pytest

from janus_tpu.obs.metrics import Registry
from janus_tpu.obs.scheduler import AdaptiveTick, SchedulerConfig


def _drive(sched, ticks, backlog, seal_ms):
    """Feed identical observations and apply every decision."""
    changes = []
    for _ in range(ticks):
        sched.observe(backlog, seal_ms)
        t = sched.maybe_adjust()
        if t is not None:
            changes.append(t)
    return changes


def test_trickle_with_slow_seal_shrinks_to_floor():
    cfg = SchedulerConfig(b_min=64, b_max=5120, window=8,
                          latency_target_ms=50.0, adjust_every=2)
    sched = AdaptiveTick(cfg, b0=5120, registry=Registry())
    changes = _drive(sched, 40, backlog=10, seal_ms=400.0)
    assert sched.b == 64          # at the floor...
    assert changes[-1] == 64
    assert all(c >= 64 for c in changes)  # ...never below it
    # multiplicative descent: strictly decreasing targets
    assert changes == sorted(changes, reverse=True)


def test_fast_seal_never_shrinks():
    cfg = SchedulerConfig(b_min=64, b_max=5120, window=8,
                          latency_target_ms=50.0, adjust_every=2)
    sched = AdaptiveTick(cfg, b0=1024, registry=Registry())
    # drained queues but seals already under target: leave B alone
    assert _drive(sched, 20, backlog=10, seal_ms=5.0) == []
    assert sched.b == 1024


def test_saturation_grows_to_ceiling():
    cfg = SchedulerConfig(b_min=64, b_max=5120, window=8,
                          grow_step=512, adjust_every=2)
    sched = AdaptiveTick(cfg, b0=64, registry=Registry())
    changes = _drive(sched, 60, backlog=10_000, seal_ms=5.0)
    assert sched.b == 5120        # reached the swept peak
    # additive ascent: strictly increasing
    assert changes == sorted(changes)


def test_delta_overflow_is_shrink_pressure():
    cfg = SchedulerConfig(b_min=64, b_max=5120, window=8,
                          latency_target_ms=50.0, adjust_every=2)
    sched = AdaptiveTick(cfg, b0=5120, registry=Registry())
    # saturated AND fast — would normally hold/grow — but the delta
    # path overflowed its budget on a majority of ticks: shrink
    for _ in range(4):
        sched.observe(10_000, 5.0)
        sched.observe_delta(0.5, overflowed=True)
        t = sched.maybe_adjust()
    assert sched.b < 5120
    # minority overflow changes nothing: saturation still grows
    sched2 = AdaptiveTick(cfg, b0=1024, registry=Registry())
    for i in range(4):
        sched2.observe(10_000, 5.0)
        sched2.observe_delta(0.1, overflowed=(i == 0))
        sched2.maybe_adjust()
    assert sched2.b > 1024


def test_never_exceeds_ring_window_bound():
    # W x B must stay under max_inflight_ops: bound = 1024 // 8 = 128
    cfg = SchedulerConfig(b_min=32, b_max=5120, window=8,
                          max_inflight_ops=1024, quantum=32,
                          grow_step=512, adjust_every=2)
    sched = AdaptiveTick(cfg, b0=5120, registry=Registry())
    assert sched.b <= 128         # clamped at construction already
    _drive(sched, 40, backlog=10_000, seal_ms=1.0)
    assert sched.b <= 128
    assert sched.b * cfg.window <= cfg.max_inflight_ops


def test_targets_quantize():
    cfg = SchedulerConfig(b_min=64, b_max=5000, window=8, quantum=64,
                          grow_step=500, adjust_every=2)
    sched = AdaptiveTick(cfg, b0=64, registry=Registry())
    changes = _drive(sched, 60, backlog=10_000, seal_ms=1.0)
    assert changes, "controller never grew"
    for c in changes:
        assert c % 64 == 0


def test_oscillation_recovers_after_load_returns():
    cfg = SchedulerConfig(b_min=64, b_max=2048, window=8,
                          latency_target_ms=50.0, grow_step=512,
                          adjust_every=2)
    sched = AdaptiveTick(cfg, b0=2048, registry=Registry())
    _drive(sched, 30, backlog=5, seal_ms=300.0)
    assert sched.b == 64
    _drive(sched, 30, backlog=50_000, seal_ms=5.0)
    assert sched.b == 2048


# -- actuation: SafeKV.resize_block --------------------------------------

@pytest.fixture(scope="module")
def small_kv():
    from janus_tpu.consensus import DagConfig
    from janus_tpu.models import pncounter
    from janus_tpu.runtime.safecrdt import SafeKV

    return SafeKV(DagConfig(4, 8), pncounter.SPEC, ops_per_block=8,
                  num_keys=8, num_writers=4)


def _batch(kv, n_ops):
    from janus_tpu.models import base

    n, B = kv.cfg.num_nodes, kv.B
    op = np.zeros((n, B), np.int32)
    key = np.zeros((n, B), np.int32)
    a0 = np.zeros((n, B), np.int32)
    writer = np.broadcast_to(
        np.arange(n, dtype=np.int32)[:, None], (n, B)).copy()
    op[:, :n_ops] = kv.spec.op_codes["i"]
    a0[:, :n_ops] = 1
    return base.make_op_batch(op=op, key=key, a0=a0, writer=writer)


def _prospective_sum(kv):
    return int(np.asarray(kv.query_prospective("get")).sum())


def test_resize_block_grow_preserves_state(small_kv):
    kv = small_kv
    for _ in range(4):
        kv.step(_batch(kv, 2))
    before = _prospective_sum(kv)
    assert before > 0
    assert kv.resize_block(16)
    assert kv.B == 16
    assert kv.ops_buffer["op"].shape[2] == 16
    # committed/prospective state survives the geometry change
    assert _prospective_sum(kv) == before
    # and the runtime still steps (retraces) at the new shape
    kv.step(_batch(kv, 3))
    assert _prospective_sum(kv) > before


def test_resize_block_shrink_refused_while_tail_live(small_kv):
    kv = small_kv
    # park ops in tail lanes (beyond the shrink target) of the current
    # window slot, then immediately ask to shrink under them
    kv.step(_batch(kv, kv.B))
    b_before = kv.B
    assert not kv.resize_block(4), (
        "shrink must refuse while tail lanes hold live ops")
    assert kv.B == b_before
    # after the ring recycles those slots, the same shrink succeeds
    for _ in range(4 * kv.cfg.num_rounds):
        kv.step(_batch(kv, 2))
        if kv.resize_block(4):
            break
    assert kv.B == 4
    kv.step(_batch(kv, 2))  # still steps at the shrunken shape


def test_resize_block_noop_same_size(small_kv):
    kv = small_kv
    assert kv.resize_block(kv.B)


# -- SLO mode: the shed/wait overload laws --------------------------------

def _slo_sched(**kw):
    cfg = SchedulerConfig(b_min=64, b_max=1024, window=8, adjust_every=1,
                          slo_p99_target_ms=100.0, wait0_ms=10.0,
                          wait_min_ms=1.0, wait_max_ms=50.0, **kw)
    return AdaptiveTick(cfg, b0=256, registry=Registry())


def _slo_tick(s, goodput, p99, depth):
    s.observe(0, 1.0)
    s.observe_slo(goodput, p99, depth)
    s.maybe_adjust()


def test_slo_overload_grows_shed_and_pins_wait():
    s = _slo_sched()
    _slo_tick(s, goodput=1000.0, p99=500.0, depth=1.2)
    assert s.shed_prob == pytest.approx(0.05)
    assert s.wait_ms == 50.0            # deep queue: batching is free
    # sustained overload: multiplicative ascent, capped at shed_max
    seen = [s.shed_prob]
    for _ in range(20):
        _slo_tick(s, goodput=1000.0, p99=500.0, depth=1.2)
        seen.append(s.shed_prob)
    assert seen == sorted(seen)          # monotone under sustained load
    assert seen[-1] == pytest.approx(0.95)  # and never past the ceiling


def test_slo_deep_queue_alone_sheds_before_p99_breach():
    # depth >= 1.0 sheds even while p99 still looks fine: the queue
    # will become latency next window
    s = _slo_sched()
    _slo_tick(s, goodput=1000.0, p99=5.0, depth=1.5)
    assert s.shed_prob > 0.0


def test_slo_goodput_guard_backs_shed_off_during_collapse():
    s = _slo_sched()
    # establish a healthy goodput peak first
    for _ in range(3):
        _slo_tick(s, goodput=1000.0, p99=5.0, depth=0.0)
    # overload arrives WITH collapsed goodput (< 90% of peak): growing
    # shed would trade throughput for nothing, so the law backs off —
    # from zero it stays at zero
    _slo_tick(s, goodput=500.0, p99=500.0, depth=1.2)
    assert s.shed_prob == 0.0
    assert s.wait_ms == 50.0             # hold-off still pins long
    # goodput back near peak: the ascent resumes
    _slo_tick(s, goodput=990.0, p99=500.0, depth=1.2)
    assert s.shed_prob == pytest.approx(0.05)
    # ramp shed up while goodput holds, then collapse goodput: the law
    # must DECREASE shed (AIMD seeking the plateau), not pin it high —
    # a held overshoot is a permanent goodput collapse
    for _ in range(6):
        _slo_tick(s, goodput=990.0, p99=500.0, depth=1.2)
    high = s.shed_prob
    assert high > 0.5
    _slo_tick(s, goodput=400.0, p99=500.0, depth=1.2)
    assert s.shed_prob == pytest.approx(high * 0.7)
    # sustained collapse keeps backing off until goodput recovers
    for _ in range(20):
        _slo_tick(s, goodput=400.0, p99=500.0, depth=1.2)
    assert s.shed_prob == 0.0


def test_slo_shallow_slow_shrinks_wait_instead_of_shedding():
    s = _slo_sched()
    _slo_tick(s, goodput=1000.0, p99=500.0, depth=1.2)  # seed shed/wait
    assert s.wait_ms == 50.0
    # p99 past target but the queue is shallow: the hold-off IS the
    # latency — halve it toward the floor and decay shed instead
    waits = []
    for _ in range(8):
        _slo_tick(s, goodput=1000.0, p99=500.0, depth=0.1)
        waits.append(s.wait_ms)
    assert waits == sorted(waits, reverse=True)
    assert waits[-1] == 1.0              # at wait_min
    assert s.shed_prob == 0.0            # decayed below 0.02 -> snapped


def test_slo_healthy_decays_shed_and_relaxes_wait():
    s = _slo_sched()
    for _ in range(4):
        _slo_tick(s, goodput=1000.0, p99=500.0, depth=1.2)
    assert s.shed_prob > 0.0 and s.wait_ms == 50.0
    for _ in range(12):
        _slo_tick(s, goodput=1000.0, p99=5.0, depth=0.0)
    assert s.shed_prob == 0.0
    # wait converges halfway per tick back to the operating point
    assert abs(s.wait_ms - 10.0) < 0.5


def test_slo_laws_inert_without_target():
    # slo_p99_target_ms = 0 (the default): observe_slo evidence is
    # accepted but the shed/wait laws never engage — pre-overload
    # deployments keep the plain block-size controller
    cfg = SchedulerConfig(b_min=64, b_max=1024, window=8, adjust_every=1,
                          wait0_ms=10.0)
    s = AdaptiveTick(cfg, b0=256, registry=Registry())
    for _ in range(5):
        _slo_tick(s, goodput=1000.0, p99=500.0, depth=2.0)
    assert s.shed_prob == 0.0
    assert s.wait_ms == 10.0
