"""Bounded-ring liveness under crash faults: a run of dead-leader waves
(crashed leaders whose anchors never exist) must not deadlock the ring.

Measured round-5 failure this guards: with n=8, nodes {6,7} crashed, and
seed-0 leader election, waves 6,7,8 all elect crashed leaders — a 3-wave
dead run. A W=8 ring holds W/2=4 waves in flight; once the run spans the
window's tail, certified-but-uncommitted blocks keep `can_gain` true,
the GC frontier freezes (base_round stuck at 10), back-pressure rejects
every submission, and the cluster halts forever. The reference never
deadlocks only because its DAG grows without bound (DAG.cs GC comment
:946-965); the bounded ring's liveness contract is W/2 > longest
dead-leader run + 2, so fault deployments size W accordingly (the
harness fault presets use window=16)."""
import numpy as np

from janus_tpu.consensus import DagConfig
from janus_tpu.consensus.tusk import leader_of
from janus_tpu.models import base, pncounter
from janus_tpu.runtime.safecrdt import SafeKV

N, B, K = 8, 8, 16
CRASHED = 2


def _drive(window: int, ticks: int):
    kv = SafeKV(DagConfig(N, window), pncounter.SPEC, ops_per_block=B,
                collect_logs=False, num_keys=K, num_writers=N)
    rng = np.random.default_rng(0)
    active = np.ones(N, bool)
    active[-CRASHED:] = False
    accepted_by_tick = []
    for _ in range(ticks):
        ops = base.make_op_batch(
            op=np.where(active[:, None],
                        rng.integers(1, 3, (N, B)), 0).astype(np.int32),
            key=rng.integers(0, K, (N, B)).astype(np.int32),
            a0=rng.integers(1, 10, (N, B)).astype(np.int32),
            writer=np.broadcast_to(
                np.arange(N, dtype=np.int32)[:, None], (N, B)).copy())
        info = kv.step(ops, active=active, record=True)
        accepted_by_tick.append(int(info["accepted"][:N - CRASHED].sum()))
    return kv, accepted_by_tick


def test_seed0_leader_mix_has_a_dead_run():
    """The scenario premise: waves 6-8 elect crashed leaders (a 3-run)."""
    cfg = DagConfig(N, 8)
    dead = {N - CRASHED + i for i in range(CRASHED)}
    leaders = [int(leader_of(cfg, w, seed=0)) for w in range(10)]
    assert all(l in dead for l in leaders[6:9]), leaders


def test_w8_ring_deadlocks_and_w16_survives():
    # W=8: the 3-run spans the 4 in-flight waves -> full halt (every
    # live submission rejected for the rest of the run)
    kv8, acc8 = _drive(window=8, ticks=40)
    assert acc8[-1] == 0 and acc8[-5:] == [0] * 5, acc8[-10:]
    frozen_base = kv8.base_round()

    # W=16: 8 waves in flight ride out the run; submissions keep
    # landing, commits keep flowing, and the GC frontier passes the
    # point where the small ring froze
    kv16, acc16 = _drive(window=16, ticks=40)
    assert acc16[-1] == N - CRASHED, acc16[-10:]
    assert all(a == N - CRASHED for a in acc16[-10:])
    assert kv16.stats["own_commits"] > kv8.stats["own_commits"]
    assert kv16.base_round() > frozen_base
