"""Launcher + logging + client-codec unit tests (round-5 additions):
remote ssh/scp deployment command shapes (reference
start_servers.py:137-162), the --log-level verbosity plumbing
(Globals.cs:16-49 analog), and reply-codec robustness to truncation."""
import importlib.util
import json
import logging
import os
import sys

import pytest

spec = importlib.util.spec_from_file_location(
    "start_split_cluster",
    os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                 "start_split_cluster.py"))
launcher = importlib.util.module_from_spec(spec)
spec.loader.exec_module(launcher)


def test_remote_start_cmds_shape():
    cmds = launcher.remote_start_cmds(
        "ubuntu@10.0.0.1", "/home/ubuntu/janus", "/tmp/x/proc0.json", 0,
        "/tmp/janus_split", "debug")
    assert cmds[0][:2] == ["ssh", "ubuntu@10.0.0.1"]
    assert cmds[1][0] == "scp" and cmds[1][-1].endswith(":/tmp/janus_split/proc0.json")
    start = cmds[2][2]
    assert "cd /home/ubuntu/janus" in start
    assert "-m janus_tpu.net.service" in start
    assert "--log-level debug" in start
    assert start.endswith("echo $!")  # pid collection


def test_remote_deploy_cmds_shape():
    cmds = launcher.remote_deploy_cmds("u@h", "/w")
    assert cmds[0] == ["ssh", "u@h", "mkdir -p /w"]
    assert cmds[1][0] == "rsync" and cmds[1][-1] == "u@h:/w/"


def test_start_remote_collects_ssh_pid(tmp_path, monkeypatch):
    calls = []

    class Out:
        stdout = "12345\n"

    def fake_run(cmd, **kw):
        calls.append(cmd)
        return Out()

    monkeypatch.setattr(launcher, "_run", fake_run)
    cfg = {
        "num_nodes": 2, "window": 8, "ops_per_block": 8,
        "types": [{"type_code": "pnc", "dims": {"num_keys": 8}}],
        "procs": [
            {"address": "10.0.0.1", "dag_port": 7100, "owned": [0],
             "client_port": 5100, "ssh": "u@10.0.0.1", "workdir": "/w"},
        ],
    }
    p = tmp_path / "cluster.json"
    p.write_text(json.dumps(cfg))
    launcher.start(str(p), str(tmp_path / "logs"), "info")
    pids = (tmp_path / "logs" / "pids").read_text().split()
    assert pids == ["u@10.0.0.1:12345"]
    assert any(c[0] == "scp" for c in calls)
    # the shipped per-proc config carries the log level
    shipped = json.loads((tmp_path / "logs" / "proc0.json").read_text())
    assert shipped["log_level"] == "info"
    assert shipped["proc_index"] == 0


def test_start_hosts_mode_spawns_services_and_federation(tmp_path,
                                                         monkeypatch):
    """Service-hosts mode (ISSUE 17): one standalone sharded service
    per host row — per-host JSON must NOT carry the topology blocks
    (shards > 1 + procs is a config error service-side) — plus one
    federation scoreboard process peered at every host's obs port."""
    spawned = []

    def fake_popen(cmd, **kw):
        spawned.append(cmd)

        class Child:
            pid = 40000 + len(spawned)

        return Child()

    monkeypatch.setattr(launcher.subprocess, "Popen", fake_popen)
    cfg = {
        "num_nodes": 2, "window": 8, "ops_per_block": 8,
        "shards": 2, "native_demux": True,
        "types": [{"type_code": "pnc", "dims": {"num_keys": 8}}],
        "federation": {"port": 9100},
        "hosts": [
            {"client_port": 5100, "obs_port": 9101},
            {"client_port": 5101, "obs_port": 9102, "shards": 4},
        ],
    }
    p = tmp_path / "cluster.json"
    p.write_text(json.dumps(cfg))
    logs = tmp_path / "logs"
    launcher.start(str(p), str(logs), "warning")
    host0 = json.loads((logs / "host0.json").read_text())
    host1 = json.loads((logs / "host1.json").read_text())
    for h in (host0, host1):
        assert "procs" not in h and "hosts" not in h
        assert "federation" not in h
        assert h["native_demux"] is True
        assert h["log_level"] == "warning"
    assert host0["port"] == 5100 and host0["obs_port"] == 9101
    assert host0["shards"] == 2
    assert host1["shards"] == 4  # host row overrides the top level
    # 2 service hosts + 1 federation scoreboard, all in the pids file
    assert len(spawned) == 3
    assert (logs / "pids").read_text().split() == [
        "40001", "40002", "40003"]
    fed = spawned[2]
    assert "janus_tpu.obs.httpexp" in fed
    assert "h0=http://127.0.0.1:9101" in fed
    assert "h1=http://127.0.0.1:9102" in fed


def test_log_configure_levels():
    from janus_tpu.utils.log import LEVELS, configure, get_logger
    configure("warning")
    root = logging.getLogger("janus")
    assert root.level == logging.WARNING
    lg = get_logger("fabric", "p3")
    assert lg.name == "janus.fabric.p3"
    assert not lg.isEnabledFor(logging.INFO)
    configure("debug")
    assert lg.isEnabledFor(logging.DEBUG)
    with pytest.raises(ValueError):
        configure("loud")
    assert set(LEVELS) == {"debug", "info", "warning", "error", "off"}
    configure("info")


def test_decode_reply_truncated_field_is_safe():
    from janus_tpu.net.client import _varint, decode_reply
    # field 9 (payload, wire type 2) claiming 100 bytes but truncated
    evil = _varint(2 << 3) + _varint(7) + _varint(9 << 3 | 2) + _varint(100)
    out = decode_reply(evil + b"abc")
    assert out["seq"] == 7          # fields before the truncation parse
    assert out["payload"] == ""     # truncated field ignored, no raise


def test_service_log_level_cli_parse(tmp_path):
    # the service main's flag parsing: --log-level anywhere in argv
    from janus_tpu.net.service import JanusConfig
    cfg = JanusConfig.from_json(json.dumps({"log_level": "debug"}))
    assert cfg.log_level == "debug"
