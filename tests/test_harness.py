"""Benchmark-harness smoke tests: tiny configs through both drive modes
plus the banking app (reference: BenchmarkRunners/BankingBenchmark end to
end with real clients, Tests/KVStoreTests.cs-style single-process)."""
import numpy as np

from janus_tpu.bench.banking import BankingConfig, run_banking
from janus_tpu.bench.harness import PRESETS, BenchConfig, run_tensor, run_wire


def test_tensor_mode_pnc_small():
    cfg = BenchConfig(name="t", type_code="pnc", num_nodes=4, window=8,
                      num_objects=16, ops_per_block=8, ticks=20,
                      ops_ratio=(0.2, 0.4, 0.4))
    res = run_tensor(cfg)
    d = res.to_dict()
    assert d["throughput_ops_per_sec"] > 0
    assert d["latency"]["safeUpdate"]["count"] > 0
    assert d["latency"]["get"]["count"] > 0


def test_tensor_mode_byzantine_small():
    cfg = BenchConfig(name="b", type_code="pnc", num_nodes=4, window=16,
                      num_objects=16, ops_per_block=8, ticks=24,
                      byzantine=1, invalid_rate=0.5,
                      ops_ratio=(0.0, 0.5, 0.5))
    res = run_tensor(cfg)
    assert res.to_dict()["throughput_ops_per_sec"] > 0
    assert res.extra["pruned_blocks"] > 0


def test_wire_mode_small():
    cfg = BenchConfig(name="w", mode="wire", type_code="pnc", num_nodes=4,
                      window=8, num_objects=8, clients=2, ops_per_client=10,
                      ops_ratio=(0.4, 0.4, 0.2))
    res = run_wire(cfg)
    d = res.to_dict()
    assert res.total_ops == 20
    assert d["latency"]["safeUpdate"]["count"] > 0
    assert d["server_stats"]["ops_received"] > 0


def test_banking_small():
    cfg = BankingConfig(num_accounts=8, clients=2, txns_per_client=12,
                        ops_per_block=16, initial_balance=500)
    res = run_banking(cfg)
    d = res.to_dict()
    assert res.total_txns == 24
    assert d["tps"] > 0
    assert sum(s.get("count", 0) for s in d["latency"].values()) == 24


def test_presets_loadable():
    for name, cfg in PRESETS.items():
        assert cfg.num_nodes >= 4, name
        assert BenchConfig.from_json(
            __import__("json").dumps({"name": name})).name == name


def test_rga_replay_small():
    from janus_tpu.bench.harness import run_rga_replay
    cfg = BenchConfig(name="rga-s", type_code="rga", num_nodes=8,
                      num_objects=4, ops_per_block=8, ticks=6)
    res = run_rga_replay(cfg)
    d = res.to_dict()
    assert d["throughput_ops_per_sec"] > 0
    assert res.extra["elements_per_doc"] > 0
    assert not res.extra["depth_overflow"]
