"""DAG + Tusk scenario tests.

Replays the reference's deterministic consensus scenarios
(Tests/DAGTests.cs: genesis :70-102, certificate at 2f+1 :104-135, round
advance :137-156, first consensus + cross-replica ordered equality
:158-187, multi-round commit math :190-224, 100 rounds :226-271, stall
with <2f+1 certs :273-344, faulty-rate liveness :1308-1453) with delivery
masks instead of hand-pumped message queues."""
import jax.numpy as jnp
import numpy as np
import pytest

from janus_tpu.consensus import (
    DagConfig,
    advance_rounds,
    commit_view,
    create_blocks,
    deliver_blocks,
    deliver_certificates,
    form_certificates,
    init,
    init_commit,
    leaders,
    ordered_blocks,
    round_step,
    sign_blocks,
)

CFG4 = DagConfig(num_nodes=4, num_rounds=16)


def test_quorum_math():
    assert CFG4.f == 1 and CFG4.quorum == 3
    assert DagConfig(7, 4).f == 2 and DagConfig(7, 4).quorum == 5


def test_genesis_blocks_and_certificates():
    st = init(CFG4)
    st = create_blocks(CFG4, st)
    assert np.asarray(st["block_exists"])[0].all()      # all 4 genesis blocks
    assert not np.asarray(st["block_exists"])[1:].any()
    st = deliver_blocks(CFG4, st)
    st = sign_blocks(CFG4, st)
    assert np.asarray(st["acks"])[0].sum() == 16        # everyone signs all
    st = form_certificates(CFG4, st)
    assert np.asarray(st["cert_exists"])[0].all()


def test_round_advance_needs_quorum_certs():
    st = init(CFG4)
    st = create_blocks(CFG4, st)
    st = deliver_blocks(CFG4, st)
    st = sign_blocks(CFG4, st)
    st = form_certificates(CFG4, st)
    # certs exist but were never broadcast: only own certs held -> 1 < 2f+1
    st = advance_rounds(CFG4, st)
    assert (np.asarray(st["node_round"]) == 0).all()
    st = deliver_certificates(CFG4, st)
    st = advance_rounds(CFG4, st)
    assert (np.asarray(st["node_round"]) == 1).all()


def test_synchronous_rounds_progress():
    cfg = DagConfig(4, 32)
    st = init(cfg)
    for _ in range(100):  # window-capped
        st = round_step(cfg, st)
    assert (np.asarray(st["node_round"]) == cfg.num_rounds - 1).all()
    # every created block got certified
    created = np.asarray(st["block_exists"])
    certed = np.asarray(st["cert_exists"])
    np.testing.assert_array_equal(created[:-1], certed[:-1])


def test_stall_without_quorum():
    """Only 2 of 4 nodes participate -> no certificates -> no advancement
    (reference stall test :273-344)."""
    cfg = CFG4
    st = init(cfg)
    active = jnp.asarray([True, True, False, False])
    for _ in range(5):
        st = round_step(cfg, st, active=active)
    assert (np.asarray(st["node_round"]) == 0).all()
    assert not np.asarray(st["cert_exists"]).any()


def test_three_of_four_is_live():
    cfg = CFG4
    st = init(cfg)
    active = jnp.asarray([True, True, True, False])
    for _ in range(6):
        st = round_step(cfg, st, active=active)
    rounds = np.asarray(st["node_round"])
    assert (rounds[:3] == 6).all()
    assert rounds[3] == 0  # crashed node never moved


def test_block_without_quorum_refs_is_invalid():
    """A round>0 block with <2f+1 embedded cert references must not be
    signed (ReceivedBlock validation)."""
    cfg = CFG4
    st = init(cfg)
    st = round_step(cfg, st)  # everyone at round 1 with valid blocks
    # forge: node 0's round-1 block exists but references only 1 cert
    st = dict(st)
    st["edges"] = st["edges"].at[1, 0, :].set(jnp.asarray([True, False, False, False]))
    from janus_tpu.consensus import structural_validity
    valid = np.asarray(structural_validity(cfg, st))
    assert not valid[1, 0]
    assert valid[0].all()  # genesis always valid


def test_withheld_certificates_keep_liveness():
    """Node 3 withholds every certificate it forms (faultyRate=100 analog):
    the other nodes' certs still reach quorum and rounds advance
    (reference FaultyDAGTests liveness :1308-1453)."""
    cfg = CFG4
    st = init(cfg)
    withhold = jnp.zeros((cfg.num_rounds, 4), bool).at[:, 3].set(True)
    for _ in range(6):
        st = round_step(cfg, st, withhold=withhold)
    rounds = np.asarray(st["node_round"])
    assert (rounds >= 5).all()  # all nodes progress (3's certs never form)
    certed = np.asarray(st["cert_exists"])
    assert not certed[:, 3].any()
    assert certed[:5, :3].all()


def test_first_commit_and_cross_node_order_equality():
    """Run enough synchronous rounds for wave 0 to commit; every node
    commits the same blocks in the same total order (reference
    TestConsensus :158-187)."""
    cfg = CFG4
    st = init(cfg)
    for _ in range(4):
        st = round_step(cfg, st)
    cst = init_commit(cfg)
    cst = commit_view(cfg, st, cst)
    orders = [ordered_blocks(cfg, cst, v) for v in range(4)]
    assert all(o == orders[0] for o in orders)
    assert len(orders[0]) > 0
    # wave 0 commits the leader's causal closure: all 4 genesis blocks and
    # the leader's round-0..0 history; leader block included
    l0 = int(leaders(cfg)[0])
    assert (0, l0) in orders[0]
    # causal order: rounds ascending within the committed prefix
    rounds_in_order = [r for r, _ in orders[0]]
    assert rounds_in_order == sorted(rounds_in_order)


def test_multi_wave_commit_monotone_and_identical():
    cfg = DagConfig(4, 32)
    st = init(cfg)
    cst = init_commit(cfg)
    prefix: list = []
    for i in range(30):
        st = round_step(cfg, st)
        cst = commit_view(cfg, st, cst)
        order = ordered_blocks(cfg, cst, 0)
        assert order[: len(prefix)] == prefix  # total order only grows
        prefix = order
    # all nodes end with identical orders
    orders = [ordered_blocks(cfg, cst, v) for v in range(4)]
    assert all(o == orders[0] for o in orders)
    # every committed wave's worth of blocks: 4 blocks/round, most rounds
    assert len(orders[0]) >= 4 * 24
    # sequence numbers advanced once per anchor
    assert int(np.asarray(cst["commit_counter"])[0]) >= 10


def test_lagging_node_catches_up_in_commit():
    """Node 3 misses all broadcasts for several rounds (its view stalls),
    then delivery resumes; after full delivery its committed order equals
    the others' (reference lagging-node catch-up :697-924)."""
    cfg = DagConfig(4, 16)
    st = init(cfg)
    # mask: node 3 receives nothing
    lag = jnp.ones((4, cfg.num_rounds, 4), bool).at[3].set(False)
    act = jnp.asarray([True, True, True, True])
    for _ in range(4):
        st = create_blocks(cfg, st, act)
        st = deliver_blocks(cfg, st, lag)
        st = sign_blocks(cfg, st, lag)
        st = form_certificates(cfg, st)
        st = deliver_certificates(cfg, st, lag)
        st = advance_rounds(cfg, st)
    assert int(np.asarray(st["node_round"])[3]) == 0
    # repair: full delivery (BlockQueryMessage analog); advancement is one
    # round per check, so the caught-up node re-checks until it stops
    st = deliver_blocks(cfg, st)
    st = deliver_certificates(cfg, st)
    for _ in range(5):
        st = advance_rounds(cfg, st)
    cst = init_commit(cfg)
    cst = commit_view(cfg, st, cst)
    o3 = ordered_blocks(cfg, cst, 3)
    o0 = ordered_blocks(cfg, cst, 0)
    # node 3 commits a prefix of (or equal to) node 0's order
    assert o0[: len(o3)] == o3 and len(o3) > 0


def test_commit_skips_unsupported_wave_then_backchains():
    """Suppress wave-1 support (mask round-3 block delivery so <2f+1
    support is visible), commit -> wave 1 skipped; after repair the
    skipped leader back-chains in before wave 2's closure, and the final
    order is consistent across nodes."""
    cfg = DagConfig(4, 16)
    st = init(cfg)
    for _ in range(3):
        st = round_step(cfg, st)  # rounds 0..2 built; nodes at round 3
    # round 3: create blocks but deliver to nobody (support invisible)
    none = jnp.zeros((4, cfg.num_rounds, 4), bool)
    st = create_blocks(cfg, st)
    st = deliver_blocks(cfg, st, none)
    st = sign_blocks(cfg, st, none)  # no acks -> no certs -> no advance
    cst = init_commit(cfg)
    cst = commit_view(cfg, st, cst)
    lw_before = np.asarray(cst["last_wave"]).copy()
    assert (lw_before <= 0).all()  # wave 1 cannot have committed
    # repair: full delivery, certify, advance, continue two more rounds
    st = deliver_blocks(cfg, st)
    st = sign_blocks(cfg, st)
    st = form_certificates(cfg, st)
    st = deliver_certificates(cfg, st)
    st = advance_rounds(cfg, st)
    for _ in range(2):
        st = round_step(cfg, st)
    cst = commit_view(cfg, st, cst)
    orders = [ordered_blocks(cfg, cst, v) for v in range(4)]
    assert all(o == orders[0] for o in orders)
    assert int(np.asarray(cst["last_wave"])[0]) >= 1


@pytest.mark.parametrize("n", [4, 7])
def test_commit_order_rounds_ascending_per_seq(n):
    cfg = DagConfig(n, 16)
    st = init(cfg)
    cst = init_commit(cfg)
    for _ in range(10):
        st = round_step(cfg, st)
    cst = commit_view(cfg, st, cst)
    com = np.asarray(cst["committed"][0])
    seq = np.asarray(cst["commit_seq"][0])
    assert com.any()
    # within one anchor batch, blocks span rounds <= anchor round; seqs
    # are dense from 0
    seqs = np.unique(seq[com])
    np.testing.assert_array_equal(seqs, np.arange(len(seqs)))
