"""Client-plane end-to-end tests: framed wire messages round-trip
through the native TCP server into the consensus runtime and back.

Reference analog: the full-system KVStoreTests (Tests/KVStoreTests.cs:
16-365 — complete server stacks in one process driven over loopback
TCP) and the safe-update blocking semantics test (:289-354).
"""
import numpy as np
import pytest

from janus_tpu.net import (
    JanusClient,
    JanusConfig,
    JanusService,
    TypeConfig,
    ecdsa_available,
    ecdsa_keygen,
    ecdsa_sign,
    ecdsa_verify,
    sha256,
)


@pytest.fixture(scope="module")
def service():
    cfg = JanusConfig(
        num_nodes=4, window=8, ops_per_block=8,
        types=(TypeConfig("pnc", {"num_keys": 16}),
               TypeConfig("orset", {"num_keys": 16, "capacity": 32})),
    )
    svc = JanusService(cfg)
    port = svc.start()
    yield svc, port
    svc.stop()


def test_native_sha256_known_vector():
    assert sha256(b"abc").hex() == (
        "ba7816bf8f01cfea414140de5dae2223b00361a396177a9cb410ff61f20015ad"
    )


def test_native_ecdsa_roundtrip():
    if not ecdsa_available():
        pytest.skip("libcrypto unavailable")
    priv, pub = ecdsa_keygen()
    sig = ecdsa_sign(priv, b"janus block digest")
    assert ecdsa_verify(pub, b"janus block digest", sig)
    assert not ecdsa_verify(pub, b"tampered", sig)


def test_pnc_end_to_end(service):
    svc, port = service
    with JanusClient("127.0.0.1", port) as c:
        assert c.request("pnc", "acct", "s")["result"] == "success"
        assert c.request("pnc", "acct", "i", ["5"])["result"] == "success"
        assert c.request("pnc", "acct", "i", ["2"])["result"] == "success"
        # read-your-writes on the prospective path
        assert c.request("pnc", "acct", "gp")["result"] == "7"


def test_pnc_safe_update_deferred_ack(service):
    svc, port = service
    with JanusClient("127.0.0.1", port) as c:
        c.request("pnc", "bank", "s")
        r = c.request("pnc", "bank", "d", ["3"], is_safe=True, timeout=60)
        # the reply only arrives after consensus committed the block
        assert r["response"] == "su"
        assert r["result"] == "success"
        # the safe decrement is in the stable state
        assert c.request("pnc", "bank", "gs", timeout=60)["result"] == "-3"


def test_unknown_key_and_bad_op(service):
    svc, port = service
    with JanusClient("127.0.0.1", port) as c:
        assert "error" in c.request("pnc", "ghost", "i", ["1"])["result"]
        c.request("pnc", "k2", "s")
        assert "error" in c.request("pnc", "k2", "zz")["result"]


def test_orset_add_contains_remove(service):
    svc, port = service
    with JanusClient("127.0.0.1", port) as c:
        c.request("orset", "tags", "s")
        c.request("orset", "tags", "a", ["42"])
        assert c.request("orset", "tags", "gp", ["42"])["result"] == "true"
        # non-numeric elements go through the interner
        c.request("orset", "tags", "a", ["hello"])
        assert c.request("orset", "tags", "gp", ["hello"])["result"] == "true"
        assert c.request("orset", "tags", "gp", ["absent"])["result"] == "false"
        c.request("orset", "tags", "r", ["42"])
        assert c.request("orset", "tags", "gp", ["42"])["result"] == "false"
        # safe add: ack deferred until committed, then stably visible
        r = c.request("orset", "tags", "a", ["77"], is_safe=True, timeout=60)
        assert r["response"] == "su"
        assert c.request("orset", "tags", "gs", ["77"], timeout=60)["result"] == "true"


def test_stats_command(service):
    svc, port = service
    import json
    with JanusClient("127.0.0.1", port) as c:
        c.request("pnc", "statk", "s", timeout=60)
        c.request("pnc", "statk", "i", ["1"])
        rep = json.loads(c.request("stats", "_", "g")["result"])
        assert rep["ops_received"] > 0
        assert rep["ticks"] > 0
        assert rep["perf"]["total"] > 0
        assert rep["step_ms_p50"] > 0
        t = rep["types"]["pnc"]
        assert t["ticks"] > 0 and t["blocks_submitted"] > 0
        assert t["own_commits"] > 0 and t["keys"] >= 1


def test_multiple_clients_converge(service):
    svc, port = service
    with JanusClient("127.0.0.1", port) as a, JanusClient("127.0.0.1", port) as b:
        a.request("pnc", "shared", "s")
        b.request("pnc", "shared", "s")
        for _ in range(5):
            a.request("pnc", "shared", "i", ["1"])
            b.request("pnc", "shared", "i", ["10"])
        # both clients (different home nodes) converge on the total
        deadline = 60
        import time
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline:
            va = int(a.request("pnc", "shared", "gp", timeout=60)["result"])
            vb = int(b.request("pnc", "shared", "gp", timeout=60)["result"])
            if va == vb == 55:
                break
            time.sleep(0.05)
        assert va == vb == 55


def test_oversized_param_rejected_not_fatal(service):
    svc, port = service
    with JanusClient("127.0.0.1", port) as c:
        c.request("pnc", "big", "s")
        r = c.request("pnc", "big", "i", [str(2**32)])
        assert "error" in r["result"]
        # service survives: normal traffic still works
        assert c.request("pnc", "big", "i", ["1"])["result"] == "success"
        assert c.request("pnc", "big", "gp", timeout=60)["result"] == "1"


def test_read_your_writes_past_block_capacity(service):
    # more pipelined updates than fit one block (ops_per_block=8): the
    # read must still observe all of them (deferred until they board)
    svc, port = service
    with JanusClient("127.0.0.1", port) as c:
        c.request("pnc", "ryw", "s")
        seqs = [c.send("pnc", "ryw", "i", ["1"]) for _ in range(20)]
        got = int(c.request("pnc", "ryw", "gp", timeout=60)["result"])
        assert got == 20
        for s in seqs:
            c.wait(s, timeout=60)


def test_keyspace_replicated_through_consensus(service):
    """A key created via one client (home node A) becomes usable at a
    different client (home node B) only after its create commits; slot
    tables end identical across all views (KeySpaceManager.cs:55-113)."""
    svc, port = service
    with JanusClient("127.0.0.1", port) as a, JanusClient("127.0.0.1", port) as b:
        assert a.request("pnc", "rep-key", "s", timeout=60)["result"] == "success"
        # second client (different connection -> different home node)
        # can use it — its view materialized the same committed create
        assert b.request("pnc", "rep-key", "i", ["4"], timeout=60)["result"] == "success"
        assert a.request("pnc", "rep-key", "gp", timeout=60)["result"] == "4"
    for rt in svc.types.values():
        assert rt.rks.consistent_prefix()
        lens = {len(t) for t in rt.rks.tables}
        assert len(lens) == 1  # fully drained: identical tables


def test_cli_parse_and_repl(service):
    """CmdParser + REPL analog: commands typed as '[type] [key] [op]
    [y|n] [params]' drive the live service (CommandLineInterface.cs)."""
    import io

    from janus_tpu.net.cli import parse_command, repl

    assert parse_command("pnc k i y 5") == ("pnc", "k", "i", True, ["5"])
    assert parse_command("orset s gp 1") == ("orset", "s", "gp", False, ["1"])
    assert parse_command("bad") is None

    svc, port = service
    out = io.StringIO()
    script = io.StringIO(
        "pnc clik s\npnc clik i n 7\npnc clik gp\nquit\n")
    repl("127.0.0.1", port, inp=script, out=out)
    lines = out.getvalue().splitlines()
    assert any(l.startswith("7 ") for l in lines), lines


def test_service_process_entry_point(tmp_path):
    """Program.cs analog: the service runs as its own process from a
    JSON config, serves a client, and stops on SIGINT."""
    import json as _json
    import signal
    import subprocess
    import sys
    import time

    cfg = {"num_nodes": 4, "window": 8, "ops_per_block": 8, "port": 0,
           "types": [{"type_code": "pnc", "dims": {"num_keys": 8}}]}
    p = tmp_path / "svc.json"
    p.write_text(_json.dumps(cfg))
    # port 0 is ephemeral; have the child print it, then connect
    proc = subprocess.Popen(
        [sys.executable, "-m", "janus_tpu.net.service", str(p)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.monotonic() + 120
        line = ""
        seen = []
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if "janus-tpu service on" in line:
                break
            seen.append(line)
            if line == "" and proc.poll() is not None:
                raise AssertionError(f"service died: {''.join(seen)}")
        assert "janus-tpu service on" in line, line
        port = int(line.split("on ")[1].split()[0].split(":")[1])
        with JanusClient("127.0.0.1", port, timeout=120) as c:
            assert c.request("pnc", "x", "s", timeout=120)["result"] == "success"
            assert c.request("pnc", "x", "i", ["2"])["result"] == "success"
    finally:
        proc.send_signal(signal.SIGINT)
        assert proc.wait(timeout=30) == 0


def test_reversible_counter_compensation(service):
    """RCounter (Examples/KVDB/Client/type/RCounter.py analog): a safe
    decrement that drives the serializable value below the floor is
    compensated by its inverse; a covered decrement stands."""
    from janus_tpu.net.reversible import RCounter

    svc, port = service
    with JanusClient("127.0.0.1", port, timeout=120) as c:
        rc = RCounter(c, "rbal", floor=0, timeout=120)
        rc.increment(10)
        committed, compensated = rc.decrement(4)
        assert committed and not compensated
        assert rc.value(stable=True) == 6
        committed, compensated = rc.decrement(50)  # overdraft
        assert committed and compensated
        assert rc.value(stable=True) == 6  # restored by compensation


def test_reversible_set_bound_compensation(service):
    """RSet: the size bound is arbitrated by the serializable state, so
    it holds across clients sharing the key (unlike any local count)."""
    from janus_tpu.net.reversible import RSet

    svc, port = service
    with JanusClient("127.0.0.1", port, timeout=120) as a, \
            JanusClient("127.0.0.1", port, timeout=120) as b:
        sa = RSet(a, "bounded", max_size=2, timeout=120)
        sb = RSet(b, "bounded", max_size=2, timeout=120)
        assert sa.add("x") == (True, False)
        assert sb.add("y") == (True, False)
        committed, compensated = sa.add("z")  # third: over the bound
        assert committed and compensated
        assert sa.size(stable=True) <= 2


def test_rga_collaborative_text_over_wire():
    """Collaborative text editing through the full client plane:
    position-based inserts/deletes, reads of the materialized document,
    convergence across clients on different home nodes."""
    cfg = JanusConfig(
        num_nodes=4, window=8, ops_per_block=8,
        types=(TypeConfig("rga", {"num_keys": 2, "capacity": 64,
                                  "max_depth": 16}),),
    )
    svc = JanusService(cfg)
    port = svc.start()
    try:
        with JanusClient("127.0.0.1", port, timeout=120) as a, \
                JanusClient("127.0.0.1", port, timeout=120) as b:
            assert a.request("rga", "doc", "s", timeout=120)["result"] == "success"
            b.request("rga", "doc", "s", timeout=120)
            for i, ch in enumerate("Helo"):
                a.request("rga", "doc", "a", [str(ord(ch)), str(i)])
            # fix the typo: insert 'l' at index 3 -> "Hello"
            a.request("rga", "doc", "a", [str(ord("l")), "3"])
            assert a.request("rga", "doc", "gp", timeout=120)["result"] == "Hello"
            # another client (different home node) appends after syncing
            import time
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if b.request("rga", "doc", "gp", timeout=120)["result"] == "Hello":
                    break
                time.sleep(0.05)
            b.request("rga", "doc", "a", [str(ord("!")), "5"])
            deadline = time.monotonic() + 60
            got = None
            while time.monotonic() < deadline:
                got = a.request("rga", "doc", "gp", timeout=120)["result"]
                if got == "Hello!":
                    break
                time.sleep(0.05)
            assert got == "Hello!"
            # delete the 'H' (index 0)
            a.request("rga", "doc", "r", ["0"])
            assert a.request("rga", "doc", "gp", timeout=120)["result"] == "ello!"
            assert a.request("rga", "doc", "sp", timeout=120)["result"] == "5"
    finally:
        svc.stop()


def test_all_types_over_wire():
    """Every replicated type is wire-reachable: LWW-Set, 2P-Set,
    MVRegister, 2P2P-Graph — beyond the reference's pnc|orset surface
    (CommandController.cs:13-26 registers only those two)."""
    cfg = JanusConfig(
        num_nodes=4, window=8, ops_per_block=8,
        types=(TypeConfig("lww", {"num_keys": 4, "capacity": 16}),
               TypeConfig("tpset", {"num_keys": 4, "capacity": 16}),
               TypeConfig("mvr", {"num_keys": 4, "capacity": 8}),
               TypeConfig("graph", {"num_keys": 4, "v_capacity": 16,
                                    "e_capacity": 16})),
    )
    svc = JanusService(cfg)
    port = svc.start()
    try:
        with JanusClient("127.0.0.1", port, timeout=420) as c:
            # LWW: add then remove later wins
            c.request("lww", "s1", "s", timeout=420)
            c.request("lww", "s1", "a", ["7"])
            assert c.request("lww", "s1", "gp", ["7"], timeout=420)["result"] == "true"
            c.request("lww", "s1", "r", ["7"])
            assert c.request("lww", "s1", "gp", ["7"], timeout=420)["result"] == "false"
            # 2P: removed elements stay removed
            c.request("tpset", "s2", "s", timeout=420)
            c.request("tpset", "s2", "a", ["3"])
            c.request("tpset", "s2", "r", ["3"])
            c.request("tpset", "s2", "a", ["3"])  # no re-add in 2P
            assert c.request("tpset", "s2", "gp", ["3"], timeout=420)["result"] == "false"
            # MVRegister: single writer -> one value
            c.request("mvr", "reg", "s", timeout=420)
            c.request("mvr", "reg", "w", ["42"])
            assert c.request("mvr", "reg", "gp", ["42"], timeout=420)["result"] == "true"
            assert c.request("mvr", "reg", "sp", timeout=420)["result"] == "1"
            # Graph: vertices then an edge; removing an anchored vertex fails
            c.request("graph", "g", "s", timeout=420)
            c.request("graph", "g", "av", ["1"])
            c.request("graph", "g", "av", ["2"])
            c.request("graph", "g", "ae", ["1", "2"])
            assert c.request("graph", "g", "gp", ["1", "2"], timeout=420)["result"] == "true"
            c.request("graph", "g", "rv", ["1"])  # blocked: incident edge
            assert c.request("graph", "g", "gp", ["1"], timeout=420)["result"] == "true"
            assert c.request("graph", "g", "sp", timeout=420)["result"] == "2"
    finally:
        svc.stop()
