"""Sharded service plane (ISSUE 9): front-end router + per-shard
workers over shard_of(type_code, key). The contracts under test:

- shards=2 answers every op a shards=1 service answers, with the SAME
  final CRDT state (the router partitions the keyspace; no consensus
  instance ever spans shards);
- read-your-writes holds across the router hop (a read on a connection
  waits for that connection's earlier updates to board);
- columnar batch frames route per-key to the owning shard and the
  delta combiner preserves exact counter totals;
- stats merge across shards (counters sum, per-shard breakdown under
  "shards") and the per-shard instruments record.
"""
import json
import time

import pytest

from janus_tpu.net import JanusClient, JanusConfig, JanusService, TypeConfig
from janus_tpu.net.client import BatchSender
from janus_tpu.runtime.keyspace import shard_of

KEYS = [f"o{k}" for k in range(4)]  # shard_of("pnc", o0..o3, 2) = 0,1,0,1


def _mk_service(shards: int) -> JanusService:
    return JanusService(JanusConfig(
        num_nodes=4, window=8, ops_per_block=16, shards=shards,
        types=(TypeConfig("pnc", {"num_keys": 16}),)))


def _drive_mixed(port: int) -> dict:
    """Closed-loop mixed safe/unsafe increments over 4 keys, then read
    everything back (reads ride the same connection, so replies imply
    read-your-writes)."""
    out = {}
    with JanusClient("127.0.0.1", port, timeout=120) as c:
        for k in KEYS:
            r = c.request("pnc", k, "s", timeout=120)
            assert r["response"] != "err", r
        seqs = []
        for i in range(40):
            seqs.append(c.send("pnc", KEYS[i % 4], "i", ["2"],
                               is_safe=(i % 5 == 0)))
        pend = set(seqs)
        deadline = time.time() + 120
        while pend and time.time() < deadline:
            s, rep = c.wait_any(pend, timeout=30)
            assert rep["response"] in ("ok", "su"), rep
            pend.discard(s)
        assert not pend
        for k in KEYS:
            out[k] = c.request("pnc", k, "gp", timeout=120)["result"]
        out["stats"] = json.loads(
            c.request("stats", "_", "g", timeout=120)["result"])
    return out


def test_key_fixture_spans_both_shards():
    homes = {shard_of("pnc", k, 2) for k in KEYS}
    assert homes == {0, 1}


def test_sharded_matches_unsharded_state():
    svc1 = _mk_service(1)
    p1 = svc1.start()
    try:
        r1 = _drive_mixed(p1)
    finally:
        svc1.stop()
    svc2 = _mk_service(2)
    p2 = svc2.start()
    try:
        r2 = _drive_mixed(p2)
    finally:
        svc2.stop()
    for k in KEYS:
        assert r1[k] == r2[k], (k, r1[k], r2[k])
    # the sharded arm really was sharded, and the merge carried the
    # per-shard breakdown
    st = r2["stats"]
    assert st["shard_count"] == 2
    assert set(st["shards"]) == {"0", "1"}
    assert st["types"]["pnc"]["pending_ops"] == 0
    for snap in st["shards"].values():
        assert "pnc" in snap["types"]
        assert snap["ticks"] > 0


def test_read_your_writes_across_router():
    svc = _mk_service(2)
    port = svc.start()
    try:
        with JanusClient("127.0.0.1", port, timeout=120) as c:
            for k in KEYS:
                c.request("pnc", k, "s", timeout=120)
            # fire-and-forget unsafe increments, then read WITHOUT
            # waiting for the acks: the read must observe all of them
            for _ in range(10):
                c.send("pnc", "o0", "i", ["3"])
            got = int(c.request("pnc", "o0", "gp", timeout=120)["result"])
            assert got == 30
    finally:
        svc.stop()


def test_batch_frames_route_and_combine_exactly():
    svc = _mk_service(2)
    port = svc.start()
    try:
        with JanusClient("127.0.0.1", port, timeout=120) as c:
            for k in KEYS:
                c.request("pnc", k, "s", timeout=120)
            sender = BatchSender("127.0.0.1", port)
            # 256 increments round-robin over keys on BOTH shards, with
            # amounts that make per-key sums distinct
            idx = [i % 4 for i in range(256)]
            p0 = [1 + (i % 7) for i in range(256)]
            expect = [0] * 4
            for i, a in zip(idx, p0):
                expect[i] += a
            sender.send_frame("pnc", KEYS, idx, "i", p0=p0)
            deadline = time.time() + 120
            while time.time() < deadline:
                st = json.loads(c.request(
                    "stats", "_", "g", timeout=120)["result"])
                if st["types"]["pnc"]["pending_ops"] == 0 \
                        and st["inbox_depth"] == 0:
                    break
                time.sleep(0.05)
            sender.close()
            for k, want in zip(KEYS, expect):
                got = int(c.request("pnc", k, "gp", timeout=120)["result"])
                assert got == want, (k, got, want)
            # per-shard instruments recorded ingest on both workers
            m = st["metrics"]
            assert m["shard0_ops_total"]["value"] > 0
            assert m["shard1_ops_total"]["value"] > 0
    finally:
        svc.stop()
