"""Runtime compaction at GC fences: long-running OR-Set/RGA services
must reclaim tombstone capacity instead of filling up and dropping
slots — the principled replacement for the reference's unbounded tag
growth (196 MB messages, paper §6.2 "MessageSize") and its benchmark's
50-element reset hack (ORSetWorkload.cs:50-63).

The workload here deliberately exceeds per-key capacity in CUMULATIVE
tags (every add mints a fresh tag; every tag is eventually tombstoned):
without the fence the slots exhaust and behavior degrades; with it the
occupancy stays bounded, convergence holds bit-exactly, and membership
stays correct throughout.
"""
import dataclasses

import numpy as np

from janus_tpu.consensus import DagConfig
from janus_tpu.models import base, orset, rga
from janus_tpu.runtime.safecrdt import SafeKV

N, W, B = 4, 8, 2
K = 2
CAP = 16


def _orset_kv(spec=orset.SPEC):
    return SafeKV(DagConfig(N, W), spec, ops_per_block=B,
                  num_keys=K, capacity=CAP, rm_capacity=4)


def _churn(kv, cycles, tag_ctr_start=0):
    """Per cycle: every node adds a fresh-tagged element then removes
    it — cumulative tags far exceed CAP while live content stays tiny."""
    ctr = tag_ctr_start
    vs = np.arange(N, dtype=np.int32)
    for t in range(cycles):
        elem = np.full((N, B), 7 + (t % 3), np.int32)
        add = base.make_op_batch(
            op=np.full((N, B), orset.OP_ADD, np.int32),
            key=np.full((N, B), t % K, np.int32),
            a0=elem,
            a1=np.broadcast_to(vs[:, None], (N, B)).copy(),
            a2=np.arange(ctr, ctr + N * B, dtype=np.int32).reshape(N, B),
            writer=np.broadcast_to(vs[:, None], (N, B)).copy(),
        )
        ctr += N * B
        kv.submit(add)
        kv.tick()
        rm = base.make_op_batch(
            op=np.full((N, B), orset.OP_REMOVE, np.int32),
            key=np.full((N, B), t % K, np.int32),
            a0=elem,
            writer=np.broadcast_to(vs[:, None], (N, B)).copy(),
        )
        kv.submit(rm)
        kv.tick()
    for _ in range(2 * W):
        kv.tick()  # settle: every add is now observed everywhere
    # cleanup: remove every element value once more — observed-remove
    # semantics mean a cycle's remove missed same-cycle adds from other
    # nodes that had not yet certified at capture time
    vs = np.arange(N, dtype=np.int32)
    for e in (7, 8, 9):
        for k in range(K):
            rm = base.make_op_batch(
                op=np.full((N, B), orset.OP_REMOVE, np.int32),
                key=np.full((N, B), k, np.int32),
                a0=np.full((N, B), e, np.int32),
                writer=np.broadcast_to(vs[:, None], (N, B)).copy(),
            )
            kv.submit(rm)
            kv.tick()
    for _ in range(2 * W):
        kv.tick()  # drain: commit + apply everything
    return ctr


def test_orset_overflows_without_fence():
    """Control: the same churn with compaction disabled fills every slot
    (proving the main test's workload would overflow)."""
    spec = dataclasses.replace(orset.SPEC, compact_fence=None)
    kv = _orset_kv(spec)
    _churn(kv, 3 * CAP)
    occ = np.asarray(kv.query_prospective("element_count"))  # [N, K]
    assert int(occ.max()) == CAP, f"expected full rows, got {occ.max()}"
    assert kv.stats["compactions"] == 0


def test_orset_long_run_with_compaction():
    kv = _orset_kv()
    cycles = 3 * CAP  # 3x capacity in cumulative tags per key
    _churn(kv, cycles)
    assert kv.stats["compactions"] > 0, "GC fences never compacted"
    occ = np.asarray(kv.query_prospective("element_count"))
    assert int(occ.max()) < CAP, f"occupancy {occ.max()} not reclaimed"
    # membership stayed correct: every element was removed (cleanup
    # pass) after all its adds were observed
    for k in range(K):
        for e in (7, 8, 9):
            got = np.asarray(kv.query_prospective("contains", k, e))
            assert not got.any(), (k, e)
    # convergence is still bit-exact across views after the drain
    for f, v in kv.prospective.items():
        arr = np.asarray(v)
        for view in range(1, N):
            np.testing.assert_array_equal(arr[view], arr[0], err_msg=f)
    for f, v in kv.stable.items():
        arr = np.asarray(v)
        np.testing.assert_array_equal(arr, np.asarray(kv.prospective[f]),
                                      err_msg=f)


def test_orset_add_survives_compaction():
    """A RE-ADDED element (fresh tag after its old tags were compacted)
    stays present — compaction must never eat live tags."""
    kv = _orset_kv()
    ctr = _churn(kv, 2 * CAP)
    vs = np.arange(N, dtype=np.int32)
    add = base.make_op_batch(
        op=np.full((N, B), orset.OP_ADD, np.int32),
        key=np.zeros((N, B), np.int32),
        a0=np.full((N, B), 7, np.int32),
        a1=np.broadcast_to(vs[:, None], (N, B)).copy(),
        a2=np.arange(ctr, ctr + N * B, dtype=np.int32).reshape(N, B),
        writer=np.broadcast_to(vs[:, None], (N, B)).copy(),
    )
    kv.submit(add)
    for _ in range(2 * W):
        kv.tick()
    got = np.asarray(kv.query_prospective("contains", 0, 7))
    assert got.all()


def test_rga_churn_with_compaction():
    """Insert+delete churn past capacity: with the fence the document
    stays editable, ids never collide (the ctr_floor), and views
    converge on the same text."""
    kv = SafeKV(DagConfig(N, W), rga.SPEC, ops_per_block=B,
                num_keys=1, capacity=CAP, max_depth=8)
    vs = np.arange(N, dtype=np.int32)
    for t in range(3 * CAP):
        # one insert per tick (node 0 only): live content stays tiny
        # while cumulative elements (all eventually tombstoned) pass 3x
        # capacity — the reclaimable-tombstone regime
        op = np.zeros((N, B), np.int32)
        op[0, 0] = rga.OP_INSERT
        ins = base.make_op_batch(
            op=op,
            key=np.zeros((N, B), np.int32),
            a0=np.full((N, B), 65 + (t % 26), np.int32),
            writer=np.broadcast_to(vs[:, None], (N, B)).copy(),
        )
        kv.submit(ins)
        kv.tick()
        # delete every currently-visible element (anchored by id)
        out = kv.query_prospective("text", 0)
        live = np.asarray(out["live"])[0]
        reps = np.asarray(out["id_rep"])[0][live][:B]
        ctrs = np.asarray(out["id_ctr"])[0][live][:B]
        m = len(reps)
        if m:
            pad = ((0, 0), (0, B - m))
            dele = base.make_op_batch(
                op=np.pad(np.full((N, m), rga.OP_DELETE, np.int32), pad),
                key=np.zeros((N, B), np.int32),
                a1=np.pad(np.broadcast_to(reps[None, :], (N, m)), pad)
                    .astype(np.int32),
                a2=np.pad(np.broadcast_to(ctrs[None, :], (N, m)), pad)
                    .astype(np.int32),
                writer=np.broadcast_to(vs[:, None], (N, B)).copy(),
            )
            # only node 0 issues deletes (one deleter suffices; every
            # node deleting the same ids is also legal but noisier)
            dele = {f: np.where(np.arange(N)[:, None] == 0, v, 0)
                    for f, v in dele.items()}
            kv.submit(base.make_op_batch(**dele))
        kv.tick()
    for _ in range(2 * W):
        kv.tick()
    assert kv.stats["compactions"] > 0
    occ = np.asarray(kv.query_prospective("element_count"))
    assert int(occ.max()) < CAP, f"rga occupancy {occ.max()} not reclaimed"
    # dtype discipline: compaction must not launder bool fields into
    # int32 (an int 'live' silently turns boolean-mask reads into
    # integer gathers — the round-4 service text-duplication bug)
    import numpy as _np
    assert kv.prospective["dead"].dtype == _np.bool_
    assert kv.query_prospective("text", 0)["live"].dtype == _np.bool_
    # all views agree on the final document
    texts = []
    out = kv.query_prospective("text", 0)
    for v in range(N):
        live = np.asarray(out["live"])[v]
        chars = np.asarray(out["chr"])[v][live]
        texts.append("".join(chr(int(c)) for c in chars))
    assert all(t == texts[0] for t in texts), texts


def test_watermark_protects_live_buffered_add():
    """The fence's counter-watermark soundness case: a tag that is
    TOMBSTONED locally while its minting add still rides a live block
    must survive compaction — a lagging view replaying that add into a
    compacted (tombstone-free) row would otherwise resurrect it."""

    st = orset.init(num_keys=2, capacity=8, rm_capacity=4)
    # two tombstoned tags on key 0: ctr 5 (old, below any live add) and
    # ctr 20 (minted concurrently with the live window)
    ops = base.make_op_batch(
        op=np.array([orset.OP_ADD, orset.OP_ADD], np.int32),
        key=np.zeros(2, np.int32),
        a0=np.array([7, 7], np.int32),
        a1=np.array([0, 1], np.int32),
        a2=np.array([5, 20], np.int32))
    st = orset.apply_ops(st, ops)
    rm = base.make_op_batch(op=np.array([orset.OP_CLEAR], np.int32),
                            key=np.zeros(1, np.int32))
    prepared = orset.prepare_ops(st, rm)
    st = orset.apply_ops(st, prepared)
    assert not bool(np.asarray(orset.contains(st, 0, 7)))

    # live window: one buffered add with ctr 10 -> watermark 10
    live = base.make_op_batch(op=[orset.OP_ADD], a1=[1], a2=[10], batch=4)
    out = orset.compact_fence(st, live)

    reps = np.asarray(out["tag_rep"])[0]
    ctrs = np.asarray(out["tag_ctr"])[0]
    valid = np.asarray(out["valid"])[0]
    removed = np.asarray(out["removed"])[0]
    kept = {(int(r), int(c)) for r, c, v in zip(reps, ctrs, valid) if v}
    # ctr 20 >= watermark 10: its add could still be in flight -> the
    # sticky tombstone survives; ctr 5 < watermark: reclaimed
    assert (1, 20) in kept
    assert (0, 5) not in kept
    assert removed[valid].all()  # everything kept is still tombstoned
