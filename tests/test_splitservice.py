"""Two-OS-process split cluster, full client plane: a client op
submitted at process A reads back from process B's stable state
(VERDICT round-3 item 2's acceptance test).

Each process runs a complete JanusService (native TCP client plane +
SplitNode DAG plane + signed payload-carrying blocks); the launcher
shape matches scripts/start_split_cluster.py. Reference: one server
process per replica (start_servers.py:115-133) with clients round-
robining over servers (BenchmarkRunners.cs:106-124).
"""
import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from janus_tpu.net.client import JanusClient


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _wait_port_line(proc, deadline):
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise AssertionError("service exited before announcing port")
        if "janus-tpu service on" in line:
            return int(line.split(" on ")[1].split()[0].rsplit(":", 1)[1])
    raise AssertionError("no port line before deadline")


def test_client_op_at_A_reads_from_B_stable(tmp_path):
    ca, cb, da, db = _free_ports(4)
    cfg = {
        "num_nodes": 4, "window": 8, "ops_per_block": 8,
        "types": [{"type_code": "pnc", "dims": {"num_keys": 8}}],
        "procs": [
            {"address": "127.0.0.1", "dag_port": da, "owned": [0, 1],
             "client_port": ca},
            {"address": "127.0.0.1", "dag_port": db, "owned": [2, 3],
             "client_port": cb},
        ],
    }
    paths = []
    for i, port in enumerate((ca, cb)):
        per = dict(cfg)
        per["proc_index"] = i
        per["port"] = port
        p = tmp_path / f"proc{i}.json"
        p.write_text(json.dumps(per))
        paths.append(str(p))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = []
    try:
        for i, path in enumerate(paths):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "janus_tpu.net.service", path, str(i)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=env, cwd="/root/repo"))
        deadline = time.monotonic() + 240
        port_a = _wait_port_line(procs[0], deadline)
        port_b = _wait_port_line(procs[1], deadline)

        with JanusClient("127.0.0.1", port_a, timeout=240) as a, \
             JanusClient("127.0.0.1", port_b, timeout=240) as b:
            # create + update at A (home node in {0,1})
            assert a.request("pnc", "acct", "s", timeout=240)["result"] \
                == "success"
            assert a.request("pnc", "acct", "i", ["5"])["result"] == "success"
            r = a.request("pnc", "acct", "d", ["2"], is_safe=True,
                          timeout=240)
            assert r["response"] == "su"

            # B learns the key via the replicated create binding and its
            # committed order; the value must appear in B's STABLE state
            deadline = time.monotonic() + 120
            got = None
            while time.monotonic() < deadline:
                rep = b.request("pnc", "acct", "gs", timeout=240)
                if rep["response"] == "ok" and rep["result"] == "3":
                    got = rep["result"]
                    break
                time.sleep(0.5)
            assert got == "3", f"B never saw A's committed ops: {rep}"

            # and the reverse direction: an update at B visible at A
            assert b.request("pnc", "acct", "i", ["10"])["result"] \
                == "success"
            deadline = time.monotonic() + 120
            ok = False
            while time.monotonic() < deadline:
                rep = a.request("pnc", "acct", "gs", timeout=240)
                if rep["response"] == "ok" and rep["result"] == "13":
                    ok = True
                    break
                time.sleep(0.5)
            assert ok, f"A never saw B's ops: {rep}"
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGINT)
            except ProcessLookupError:
                pass
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
