"""Overload-control plane tests: admission at the door, priority lanes,
retry-after nacks, and the client half of the loop.

The policy under test (service._shed_unsafe / service._door_shed):
safe- and stable-class ops are NEVER shed at any depth — overload
defers them, it does not refuse them — while unsafe ops past the hard
cap (or sampled by the controller's live shed probability) are refused
with a ``shed: retry_after_ms=N`` nack that rides the ordinary err
reply, so pre-overload (v1/v2) clients degrade to a plain nack while
upgraded clients parse the hint. Every shed op stays on the ledger
(offered, never admitted), so ``offered == admitted + shed`` holds
exactly at every call site.
"""
import socket
import threading
import time

import numpy as np
import pytest

from janus_tpu.net import JanusClient, JanusConfig, JanusService, TypeConfig
from janus_tpu.net.client import (
    SHED_PREFIX,
    BatchSender,
    parse_retry_after,
)
from janus_tpu.net.service import _POLL_FIELDS, _ShardInbox


# -- retry-after parsing (wire-compat contract) ---------------------------

def test_parse_retry_after():
    assert parse_retry_after("shed: retry_after_ms=25") == 25
    # trailing text after the integer is tolerated (future servers may
    # append detail without breaking old parsers)
    assert parse_retry_after("shed: retry_after_ms=200 (door full)") == 200
    # a v1/v2-style plain nack is NOT a shed — None, not a crash
    assert parse_retry_after("error: unknown key") is None
    assert parse_retry_after("") is None
    # prefix without digits is malformed -> not a shed hint
    assert parse_retry_after(SHED_PREFIX) is None
    assert parse_retry_after(SHED_PREFIX + "x") is None


# -- _ShardInbox overflow split counters ----------------------------------

def _chunk(n, tag0=0):
    cols = {f: np.zeros(n, dt) for f, dt in _POLL_FIELDS}
    cols["client_tag"] = np.arange(tag0, tag0 + n, dtype=np.uint64)
    return cols


def test_inbox_overflow_ops_vs_episodes():
    """overflow_ops counts pressure magnitude (every op put past the
    soft cap), overflow_episodes counts crossings (edge-triggered,
    re-armed by drain) — one burst is one episode however many ops it
    parks."""
    ib = _ShardInbox(soft_cap=10)
    ib.put(_chunk(8))
    assert ib.overflow_ops == 0 and ib.overflow_episodes == 0
    ib.put(_chunk(4))   # depth 12: crossed
    ib.put(_chunk(4))   # depth 16: still the same episode
    assert ib.overflow_ops == 8
    assert ib.overflow_episodes == 1
    assert ib.hwm == 16
    drained = ib.drain()
    assert len(drained["client_tag"]) == 16
    assert ib.depth == 0
    # drain re-armed the edge: the next crossing is a NEW episode
    ib.put(_chunk(11))
    assert ib.overflow_ops == 19
    assert ib.overflow_episodes == 2
    assert ib.hwm == 16  # high watermark remembers the deepest burst
    # soft cap never sheds: every op put was handed back by drain
    assert len(ib.drain()["client_tag"]) == 11
    # empty drain keeps the poll-column shape (fields AND dtypes)
    empty = ib.drain()
    for f, dt in _POLL_FIELDS:
        assert empty[f].dtype == dt and len(empty[f]) == 0


# -- shed policy units (real service objects, no sockets) -----------------

@pytest.fixture()
def sharded_svc():
    """A sharded front + workers, CONSTRUCTED but never started: the
    shed policy methods are pure column transforms over service state,
    so they are testable without a socket or a device step."""
    svc = JanusService(JanusConfig(
        num_nodes=4, window=8, ops_per_block=8, shards=2,
        native_demux=False, inbox_hard_cap=16, retry_after_ms=25,
        types=(TypeConfig("pnc", {"num_keys": 8}),)))
    yield svc
    svc.stop()


def _mixed_poll(svc):
    """10 ops: tags 0-3 and 8-9 unsafe updates, tag 4 a flagged-safe
    update, tag 5 a create (safe by op code), tags 6-7 stable reads
    (packed two-letter codes)."""
    cols = _chunk(10)
    opc = np.full(10, ord("i"), np.int32)
    opc[5] = ord("s")
    opc[6] = ord("g") | (ord("s") << 8)
    opc[7] = ord("s") | (ord("s") << 8)
    cols["op_code"] = opc
    is_safe = np.zeros(10, np.uint8)
    is_safe[4] = 1
    cols["is_safe"] = is_safe
    return cols


def _ledger(w):
    return (int(w.slo.offered.value), int(w.slo.admitted.value),
            int(w.slo.shed.value),
            {c: int(ctr.value) for c, ctr in w.slo.shed_by_class.items()})


def test_shed_unsafe_over_hard_cap_spares_safe_and_stable(sharded_svc):
    w = sharded_svc.workers[0]
    _off0, _adm0, shed0, by0 = _ledger(w)
    kept, n_shed = w._shed_unsafe(_mixed_poll(sharded_svc), door_depth=33)
    assert n_shed == 6
    # survivors: the flagged-safe op, the create, both stable reads
    assert kept["client_tag"].tolist() == [4, 5, 6, 7]
    # one bulk nack carrying exactly the shed tags; the hint scales
    # with how far past the cap the door sits (33/16 -> 3x base 25)
    tags, payload = w._nack_bulk[-1]
    assert sorted(tags.tolist()) == [0, 1, 2, 3, 8, 9]
    assert parse_retry_after(payload) == 75
    # ledger: unsafe sheds only, counted as replied (the nack IS the
    # reply), never admitted
    _off1, _adm1, shed1, by1 = _ledger(w)
    assert shed1 - shed0 == 6
    assert by1["unsafe"] - by0["unsafe"] == 6
    assert by1["safe"] == by0["safe"]
    assert by1["stable"] == by0["stable"]
    w._nack_bulk.clear()


def test_shed_unsafe_over_cap_sheds_only_excess(sharded_svc):
    # depth 20 vs cap 16: only the 4 ops OVER the cap are shed (newest
    # unsafe first) — the rest were legitimately admitted by the door,
    # and refusing them too would collapse goodput under sustained
    # load instead of holding it at capacity
    w = sharded_svc.workers[0]
    shed0 = int(w.slo.shed.value)
    kept, n_shed = w._shed_unsafe(_mixed_poll(sharded_svc), door_depth=20)
    assert n_shed == 4
    assert kept["client_tag"].tolist() == [0, 1, 4, 5, 6, 7]
    tags, payload = w._nack_bulk[-1]
    assert sorted(tags.tolist()) == [2, 3, 8, 9]
    # hint still scales with the overshoot: ceil(20/16) = 2x base 25
    assert parse_retry_after(payload) == 50
    assert int(w.slo.shed.value) - shed0 == 4
    w._nack_bulk.clear()


def test_shed_unsafe_probability_thins_newest_tail(sharded_svc):
    w = sharded_svc.workers[1]
    w._shed_prob = 0.5
    cols = _chunk(6)
    cols["op_code"] = np.full(6, ord("i"), np.int32)
    kept, n_shed = w._shed_unsafe(cols, door_depth=0)
    # floor(6 * 0.5) = 3 shed, and the admitted prefix keeps FIFO
    # order: the NEWEST arrivals are the ones asked to retry
    assert n_shed == 3
    assert kept["client_tag"].tolist() == [0, 1, 2]
    tags, payload = w._nack_bulk[-1]
    assert sorted(tags.tolist()) == [3, 4, 5]
    # below the cap the hint stays at the configured base
    assert parse_retry_after(payload) == 25
    w._nack_bulk.clear()
    w._shed_prob = 0.0


def test_shed_unsafe_noop_below_cap_without_probability(sharded_svc):
    w = sharded_svc.workers[0]
    shed0 = int(w.slo.shed.value)
    cols = _mixed_poll(sharded_svc)
    kept, n_shed = w._shed_unsafe(cols, door_depth=16)  # at, not past
    assert n_shed == 0 and kept is cols
    assert int(w.slo.shed.value) == shed0
    assert not w._nack_bulk


def test_door_shed_admits_safe_and_stable_before_unsafe(sharded_svc):
    """Priority lanes at the front door: with room for 6 of 10 routed
    ops, all 4 safe/stable ops enter and the unsafe budget is what is
    left — the newest unsafe excess is shed."""
    svc = sharded_svc
    w = svc.workers[0]
    _off0, _adm0, shed0, by0 = _ledger(w)
    kept = svc._door_shed(w, _mixed_poll(svc), room=6, depth=12)
    # budget for unsafe = 6 - 4 non-unsafe = 2: oldest two unsafe
    # (tags 0, 1) enter with every safe/stable op
    assert kept["client_tag"].tolist() == [0, 1, 4, 5, 6, 7]
    tags, payload = svc._nack_bulk[-1]
    assert sorted(tags.tolist()) == [2, 3, 8, 9]
    # (depth + chunk) / hard = 22/16 -> hint stays 1x base
    assert parse_retry_after(payload) == 25
    _off1, _adm1, shed1, by1 = _ledger(w)
    assert shed1 - shed0 == 4
    assert by1["unsafe"] - by0["unsafe"] == 4
    assert by1["safe"] == by0["safe"] and by1["stable"] == by0["stable"]
    svc._nack_bulk.clear()


def test_door_shed_zero_room_still_admits_safe_and_stable(sharded_svc):
    svc = sharded_svc
    w = svc.workers[1]
    kept = svc._door_shed(w, _mixed_poll(svc), room=0, depth=16)
    assert kept["client_tag"].tolist() == [4, 5, 6, 7]
    svc._nack_bulk.clear()


# -- BatchSender drain scan + backoff -------------------------------------

def _accepted_pair():
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    out = {}

    def accept():
        out["conn"], _ = srv.accept()

    th = threading.Thread(target=accept)
    th.start()
    sender = BatchSender("127.0.0.1", srv.getsockname()[1], backoff=False)
    th.join()
    srv.close()
    return sender, out["conn"]


def _wait_for(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise AssertionError("condition not reached")
        time.sleep(0.01)


def test_batch_sender_counts_sheds_split_across_chunks():
    """The drain thread's substring scan must count a nack whose
    pattern bytes straddle two recv chunks exactly once (the carry is
    one byte short of the pattern, so it can never recount)."""
    sender, conn = _accepted_pair()
    pat = b"shed: retry_after_ms=40;"
    try:
        conn.sendall(b"\x00\x07ok" + pat[:9])
        time.sleep(0.05)  # force a chunk boundary mid-pattern
        conn.sendall(pat[9:])
        _wait_for(lambda: sender.shed_replies == 1)
        assert sender.retry_after_ms == 40
        # two whole nacks in one chunk count as two; the freshest hint
        # wins
        conn.sendall(b"shed: retry_after_ms=80;shed: retry_after_ms=120;")
        _wait_for(lambda: sender.shed_replies == 3)
        assert sender.retry_after_ms == 120
    finally:
        conn.close()
        sender.close()


def test_batch_sender_backoff_pays_hint_then_resets():
    sender, conn = _accepted_pair()
    try:
        conn.sendall(b"shed: retry_after_ms=40;")
        _wait_for(lambda: sender.shed_replies == 1)
        t0 = time.monotonic()
        sender._maybe_backoff()
        paid = time.monotonic() - t0
        assert sender.backoff_sleeps == 1
        # hint 40ms with +/-50% jitter: at least ~20ms actually slept
        assert paid >= 0.015
        # no NEW sheds since: the gate is free and the streak resets
        t0 = time.monotonic()
        sender._maybe_backoff()
        assert time.monotonic() - t0 < 0.01
        assert sender.backoff_sleeps == 1
        assert sender._streak == 0
    finally:
        conn.close()
        sender.close()


# -- end to end: shed nack round-trip through the real wire ---------------

def test_service_sheds_with_retry_hint_end_to_end():
    """Flood one shard's door past its hard cap through the REAL
    sharded service (python router) and read the replies back: unsafe
    excess is nacked with a parseable retry hint riding the ordinary
    err payload (v1/v2 clients degrade to a plain nack for free), a
    safe op sent at full depth is deferred and eventually acked, and
    the per-worker ledgers reconcile offered == admitted + shed."""
    svc = JanusService(JanusConfig(
        num_nodes=4, window=8, ops_per_block=8, shards=2,
        native_demux=False, inbox_hard_cap=8, retry_after_ms=25,
        types=(TypeConfig("pnc", {"num_keys": 16}),)))
    port = svc.start(pump=False)

    def pump(n=8, workers=True):
        for _ in range(n):
            svc.step()
            if workers:
                for w in svc.workers:
                    w.step()
            time.sleep(0.005)

    try:
        with JanusClient("127.0.0.1", port) as c:
            seq = c.send("pnc", "acct", "s")
            pump(8)
            assert c.wait(seq, timeout=30)["result"] == "success"
            pump(40)  # run the create through consensus

            led0 = [_ledger(w) for w in svc.workers]
            off_base = sum(int(w.slo.offered.value) for w in svc.workers)
            # 64 unsafe increments on ONE key: they all route to one
            # shard whose door (hard cap 8) admits at most 8
            seqs = c.send_batch("pnc", ["acct"], np.zeros(64, np.int32),
                                "i", p0=np.ones(64, np.int64))
            for _ in range(100):  # route + nack flush, no worker drain
                pump(1, workers=False)
                off = sum(int(w.slo.offered.value) for w in svc.workers)
                if off - off_base >= 64:
                    break
            depth = max(w._inbox_depth() for w in svc.workers)
            assert depth == 8, "door admitted past its hard cap"

            # priority lane while the queue sits AT the cap: a safe op
            # still enters (deferred), and the shed ledger does not move
            shed_mid = sum(int(w.slo.shed.value) for w in svc.workers)
            safe_seq = c.send("pnc", "acct", "i", ["1"], is_safe=True)
            pump(4, workers=False)
            assert max(w._inbox_depth() for w in svc.workers) == 9
            assert sum(int(w.slo.shed.value)
                       for w in svc.workers) == shed_mid

            pump(60)  # drain + commit so the deferred safe ack lands
            by_status = {"shed": 0, "ok": 0, "err": 0, "su": 0}
            for s in seqs:
                rep = c.wait(s, timeout=30)
                by_status[str(rep["response"])] += 1
                if rep["response"] == "shed":
                    # the hint is both a dict field and parseable out
                    # of the plain err text a v1/v2 client would see
                    assert rep["retry_after_ms"] >= 25
                    assert parse_retry_after(str(rep["result"])) \
                        == rep["retry_after_ms"]
            # 56 shed at the door; the safe op then pushed the queue
            # one PAST the cap, so the drain shed exactly the ONE
            # excess unsafe op (newest first) — the 7 the door had
            # legitimately admitted still execute, and every refused
            # op got a nack reply, none went dark
            assert by_status["shed"] == 57
            assert by_status["ok"] == 7
            assert by_status["err"] == 0
            assert c.wait(safe_seq, timeout=30)["response"] == "su"

            # below the cap the same unsafe traffic is served normally
            ok_seqs = c.send_batch("pnc", ["acct"],
                                   np.zeros(4, np.int32), "i",
                                   p0=np.ones(4, np.int64))
            pump(12)
            assert all(c.wait(s, timeout=30)["response"] == "ok"
                       for s in ok_seqs)

            # ledger reconciliation, as deltas across the flood
            d_off = d_adm = d_shed = 0
            for w, (off0, adm0, shed0, by0) in zip(svc.workers, led0):
                off1, adm1, shed1, by1 = _ledger(w)
                d_off += off1 - off0
                d_adm += adm1 - adm0
                d_shed += shed1 - shed0
                assert by1["safe"] == by0["safe"]
                assert by1["stable"] == by0["stable"]
            assert d_shed == 57
            # the flood delta reconciles: 69 offered = 12 admitted
            # (7 drained unsafe + safe op + 4 served below-cap) + 57
            # shed (56 at the door + 1 over-cap excess at the drain)
            assert d_off == 69 and d_adm == 12
            # (deltas, not cumulative values: the ledger counters live
            # in the process-global registry, which other tests in the
            # same pytest process also feed)
            assert d_off == d_adm + d_shed

            # request_with_retry honors the hint: two synthetic sheds
            # then success — three requests, final reply is the ok one
            replies = [
                {"seq": 1, "response": "shed", "retry_after_ms": 10,
                 "result": "shed: retry_after_ms=10"},
                {"seq": 2, "response": "shed", "retry_after_ms": 10,
                 "result": "shed: retry_after_ms=10"},
                {"seq": 3, "response": "ok", "result": "65"},
            ]
            calls = []

            def fake_request(*a, **k):
                calls.append(a)
                return replies[min(len(calls) - 1, len(replies) - 1)]

            c.request = fake_request
            t0 = time.monotonic()
            rep = c.request_with_retry("pnc", "acct", "i", ["1"],
                                       retries=8, backoff_cap_ms=40)
            assert rep["response"] == "ok" and len(calls) == 3
            assert time.monotonic() - t0 >= 0.01  # slept the hints
            # exhausted retries hand back the final shed reply
            calls.clear()
            always_shed = dict(replies[0])

            def fake_request_shed(*a, **k):
                calls.append(a)
                return always_shed

            c.request = fake_request_shed
            rep = c.request_with_retry("pnc", "acct", "i", ["1"],
                                       retries=2, backoff_cap_ms=20)
            assert rep["response"] == "shed" and len(calls) == 3
    finally:
        svc.stop()
