"""CRDT type-model semantics tests.

The tensor analog of the reference's per-type suites
(MergeSharp.Tests/ORSetTests.cs, LWWSetTests.cs, PNCounterTests.cs,
2PSetTests.cs, MVRegisterTests.cs, TPTPGraphTests.cs): construct 2-3
replica states, interleave ops, exchange state (merge = the reference's
GetLastSynchronizedUpdate/ApplySynchronizedUpdate), assert convergence,
add-wins / remove-permanence, idempotence.
"""
import jax.numpy as jnp
import numpy as np

from janus_tpu.models import base, graph, lwwset, mvregister, orset, pncounter, tpset


def ops(**kw):
    return base.make_op_batch(**kw)


def assert_states_equal(a, b):
    for f in a:
        np.testing.assert_array_equal(np.asarray(a[f]), np.asarray(b[f]), err_msg=f)


# ---------------------------------------------------------------------------
# PNCounter
# ---------------------------------------------------------------------------

def test_pnc_inc_dec_value():
    st = pncounter.init(num_keys=4, num_writers=3)
    st = pncounter.apply_ops(
        st,
        ops(op=[pncounter.OP_INC, pncounter.OP_INC, pncounter.OP_DEC],
            key=[0, 0, 0], a0=[5, 7, 2], writer=[0, 1, 0]),
    )
    vals = np.asarray(pncounter.value(st))
    assert vals[0] == 10 and (vals[1:] == 0).all()


def test_pnc_two_replica_convergence():
    a = pncounter.init(4, 2)
    b = pncounter.init(4, 2)
    a = pncounter.apply_ops(a, ops(op=[1, 2], key=[1, 2], a0=[10, 3], writer=[0, 0]))
    b = pncounter.apply_ops(b, ops(op=[1, 1], key=[1, 3], a0=[4, 9], writer=[1, 1]))
    ab = pncounter.merge(a, b)
    ba = pncounter.merge(b, a)
    assert_states_equal(ab, ba)
    vals = np.asarray(pncounter.value(ab))
    assert vals[1] == 14 and vals[2] == -3 and vals[3] == 9
    # idempotent re-merge
    assert_states_equal(pncounter.merge(ab, a), ab)


# ---------------------------------------------------------------------------
# ORSet
# ---------------------------------------------------------------------------

def _orset_add(st, key, elem, tag_rep, tag_ctr):
    return orset.apply_ops(
        st, ops(op=[orset.OP_ADD], key=[key], a0=[elem], a1=[tag_rep], a2=[tag_ctr])
    )


def test_orset_add_remove_contains():
    st = orset.init(num_keys=2, capacity=8)
    st = _orset_add(st, 0, 42, 0, 1)
    assert bool(orset.contains(st, 0, 42))
    assert not bool(orset.contains(st, 1, 42))
    st = orset.apply_ops(st, ops(op=[orset.OP_REMOVE], key=[0], a0=[42]))
    assert not bool(orset.contains(st, 0, 42))


def test_orset_add_wins_on_concurrent_add_remove():
    """Reference ORSetTests: remove only tombstones *observed* tags, so a
    concurrent add with a fresh tag survives the merge (add-wins)."""
    a = orset.init(1, 8)
    b = orset.init(1, 8)
    a = _orset_add(a, 0, 7, 0, 1)      # replica 0 adds
    b = orset.merge(b, a)              # replica 1 observes
    b = orset.apply_ops(b, ops(op=[orset.OP_REMOVE], key=[0], a0=[7]))  # 1 removes
    a = _orset_add(a, 0, 7, 0, 2)      # 0 concurrently re-adds (fresh tag)
    m1 = orset.merge(a, b)
    m2 = orset.merge(b, a)
    assert_states_equal(m1, m2)
    assert bool(orset.contains(m1, 0, 7))  # fresh tag not tombstoned


def test_orset_remove_wins_over_observed_add():
    a = orset.init(1, 8)
    a = _orset_add(a, 0, 7, 0, 1)
    b = orset.merge(orset.init(1, 8), a)
    b = orset.apply_ops(b, ops(op=[orset.OP_REMOVE], key=[0], a0=[7]))
    m = orset.merge(a, b)
    assert not bool(orset.contains(m, 0, 7))


def test_orset_clear_then_merge_no_resurrection():
    a = orset.init(1, 8)
    a = _orset_add(a, 0, 1, 0, 1)
    a = _orset_add(a, 0, 2, 0, 2)
    b = orset.merge(orset.init(1, 8), a)
    b = orset.apply_ops(b, ops(op=[orset.OP_CLEAR], key=[0]))
    m = orset.merge(a, b)
    assert not bool(orset.contains(m, 0, 1))
    assert not bool(orset.contains(m, 0, 2))
    assert int(orset.live_count(m)[0]) == 0


def test_orset_compact_reclaims_capacity():
    st = orset.init(1, 4)
    for i in range(4):
        st = _orset_add(st, 0, i, 0, i + 1)
    st = orset.apply_ops(st, ops(op=[orset.OP_CLEAR], key=[0]))
    st = orset.compact(st)
    assert int(np.asarray(st["valid"]).sum()) == 0  # all slots free again


# ---------------------------------------------------------------------------
# LWWSet
# ---------------------------------------------------------------------------

def _lww(st, op, key, elem, hi, lo):
    return lwwset.apply_ops(st, ops(op=[op], key=[key], a0=[elem], a1=[hi], a2=[lo]))


def test_lww_add_remove_readd():
    st = lwwset.init(1, 8)
    st = _lww(st, lwwset.OP_ADD, 0, 5, 0, 10)
    assert bool(lwwset.contains(st, 0, 5))
    st = _lww(st, lwwset.OP_REMOVE, 0, 5, 0, 20)
    assert not bool(lwwset.contains(st, 0, 5))
    st = _lww(st, lwwset.OP_ADD, 0, 5, 0, 30)
    assert bool(lwwset.contains(st, 0, 5))


def test_lww_add_wins_tie():
    st = lwwset.init(1, 8)
    st = _lww(st, lwwset.OP_ADD, 0, 5, 0, 10)
    st = _lww(st, lwwset.OP_REMOVE, 0, 5, 0, 10)  # same stamp: add wins
    assert bool(lwwset.contains(st, 0, 5))


def test_lww_remove_requires_presence():
    """Reference LWWSet.Remove only stamps when currently contained."""
    st = lwwset.init(1, 8)
    st = _lww(st, lwwset.OP_REMOVE, 0, 5, 0, 50)  # ignored: not present
    st = _lww(st, lwwset.OP_ADD, 0, 5, 0, 10)     # older add still lands
    assert bool(lwwset.contains(st, 0, 5))


def test_lww_merge_convergence():
    a = lwwset.init(2, 8)
    b = lwwset.init(2, 8)
    a = _lww(a, lwwset.OP_ADD, 0, 1, 0, 10)
    b = _lww(b, lwwset.OP_ADD, 0, 1, 0, 5)
    b = _lww(b, lwwset.OP_ADD, 1, 2, 0, 7)
    m1, m2 = lwwset.merge(a, b), lwwset.merge(b, a)
    assert_states_equal(m1, m2)
    assert bool(lwwset.contains(m1, 0, 1))
    assert bool(lwwset.contains(m1, 1, 2))
    assert_states_equal(lwwset.merge(m1, m1), m1)


# ---------------------------------------------------------------------------
# TPSet
# ---------------------------------------------------------------------------

def _tp(st, op, key, elem):
    return tpset.apply_ops(st, ops(op=[op], key=[key], a0=[elem]))


def test_tpset_no_readd_after_remove():
    st = tpset.init(1, 8)
    st = _tp(st, tpset.OP_ADD, 0, 9)
    assert bool(tpset.contains(st, 0, 9))
    st = _tp(st, tpset.OP_REMOVE, 0, 9)
    assert not bool(tpset.contains(st, 0, 9))
    st = _tp(st, tpset.OP_ADD, 0, 9)  # 2P: re-add has no effect
    assert not bool(tpset.contains(st, 0, 9))


def test_tpset_remove_requires_membership():
    st = tpset.init(1, 8)
    st = _tp(st, tpset.OP_REMOVE, 0, 9)  # not present: no tombstone recorded
    st = _tp(st, tpset.OP_ADD, 0, 9)
    assert bool(tpset.contains(st, 0, 9))


def test_tpset_merge_remove_propagates():
    a = tpset.init(1, 8)
    a = _tp(a, tpset.OP_ADD, 0, 9)
    b = tpset.merge(tpset.init(1, 8), a)
    b = _tp(b, tpset.OP_REMOVE, 0, 9)
    m1, m2 = tpset.merge(a, b), tpset.merge(b, a)
    assert_states_equal(m1, m2)
    assert not bool(tpset.contains(m1, 0, 9))


# ---------------------------------------------------------------------------
# MVRegister
# ---------------------------------------------------------------------------

def _wr(st, key, val, writer):
    return mvregister.apply_ops(
        st, ops(op=[mvregister.OP_WRITE], key=[key], a0=[val], writer=[writer])
    )


def test_mvr_sequential_overwrite():
    a = mvregister.init(1, num_writers=2, capacity=4)
    a = _wr(a, 0, 100, 0)
    b = mvregister.merge(mvregister.init(1, 2, 4), a)
    b = _wr(b, 0, 200, 1)  # causally after a's write
    m = mvregister.merge(a, b)
    vals, valid = mvregister.read(m, 0)
    live = set(np.asarray(vals)[np.asarray(valid)].tolist())
    assert live == {200}  # b's clock dominates -> overwrite


def test_mvr_concurrent_writes_merge():
    a = mvregister.init(1, 2, 4)
    b = mvregister.init(1, 2, 4)
    a = _wr(a, 0, 100, 0)
    b = _wr(b, 0, 200, 1)  # concurrent
    m1 = mvregister.merge(a, b)
    m2 = mvregister.merge(b, a)
    for m in (m1, m2):
        vals, valid = mvregister.read(m, 0)
        live = set(np.asarray(vals)[np.asarray(valid)].tolist())
        assert live == {100, 200}
    assert int(mvregister.num_values(m1)[0]) == 2


def test_mvr_local_dominates_keeps_local():
    a = mvregister.init(1, 2, 4)
    a = _wr(a, 0, 1, 0)
    stale = mvregister.init(1, 2, 4)  # empty clock: a dominates
    m = mvregister.merge(a, stale)
    assert_states_equal(m, a)


def test_mvr_no_divergence_on_equal_key_clocks():
    """Regression: with a single register-level clock, a union of concurrent
    writes and a later write that observed one of them can reach equal
    clocks with different value sets and diverge. Per-value clocks must
    converge both replicas to the dominating write."""
    a = mvregister.init(1, 2, 4)
    a = _wr(a, 0, 100, 0)                       # A: writer0 writes 100
    c = mvregister.merge(mvregister.init(1, 2, 4), a)
    d = mvregister.merge(mvregister.init(1, 2, 4), a)
    cw = mvregister.init(1, 2, 4)
    cw = _wr(cw, 0, 200, 1)                     # concurrent write of 200
    c = mvregister.merge(c, cw)                 # C: {100, 200}
    d = _wr(d, 0, 200, 1)                       # D: write observed 100 -> {200}
    m1 = mvregister.merge(c, d)
    m2 = mvregister.merge(d, c)
    assert_states_equal(m1, m2)
    vals, valid = mvregister.read(m1, 0)
    live = set(np.asarray(vals)[np.asarray(valid)].tolist())
    assert live == {200}  # D's write dominates both originals


def test_mvr_write_collapses_concurrency():
    a = mvregister.init(1, 2, 4)
    b = mvregister.init(1, 2, 4)
    a = _wr(a, 0, 100, 0)
    b = _wr(b, 0, 200, 1)
    m = mvregister.merge(a, b)          # 2 live values
    m = _wr(m, 0, 300, 0)               # new write observes both
    vals, valid = mvregister.read(m, 0)
    live = set(np.asarray(vals)[np.asarray(valid)].tolist())
    assert live == {300}
    # and it dominates both originals
    for other in (a, b):
        mm = mvregister.merge(m, other)
        v2, ok2 = mvregister.read(mm, 0)
        assert set(np.asarray(v2)[np.asarray(ok2)].tolist()) == {300}


# ---------------------------------------------------------------------------
# TPTPGraph
# ---------------------------------------------------------------------------

def _g(st, op, key=0, a0=0, a1=0):
    return graph.apply_ops(st, ops(op=[op], key=[key], a0=[a0], a1=[a1]))


def test_graph_vertex_edge_lifecycle():
    st = graph.init(1, v_capacity=8, e_capacity=8)
    st = _g(st, graph.OP_ADD_VERTEX, a0=1)
    st = _g(st, graph.OP_ADD_VERTEX, a0=2)
    assert bool(graph.contains_vertex(st, 0, 1))
    st = _g(st, graph.OP_ADD_EDGE, a0=1, a1=2)
    assert bool(graph.contains_edge(st, 0, 1, 2))
    # vertex with incident live edge cannot be removed
    st = _g(st, graph.OP_REMOVE_VERTEX, a0=1)
    assert bool(graph.contains_vertex(st, 0, 1))
    st = _g(st, graph.OP_REMOVE_EDGE, a0=1, a1=2)
    assert not bool(graph.contains_edge(st, 0, 1, 2))
    st = _g(st, graph.OP_REMOVE_VERTEX, a0=1)
    assert not bool(graph.contains_vertex(st, 0, 1))


def test_graph_edge_requires_vertices():
    st = graph.init(1, 8, 8)
    st = _g(st, graph.OP_ADD_EDGE, a0=1, a1=2)  # neither endpoint exists
    assert int(graph.edge_count(st)[0]) == 0


def test_graph_dangling_edge_filtered_after_merge():
    """Concurrent remove-vertex / add-edge: the edge survives in state but
    LookupEdges filters it (reference TPTPGraph.LookupEdges :139-154)."""
    a = graph.init(1, 8, 8)
    a = _g(a, graph.OP_ADD_VERTEX, a0=1)
    a = _g(a, graph.OP_ADD_VERTEX, a0=2)
    b = graph.merge(graph.init(1, 8, 8), a)
    a = _g(a, graph.OP_ADD_EDGE, a0=1, a1=2)       # concurrent add-edge
    b = _g(b, graph.OP_REMOVE_VERTEX, a0=2)        # concurrent remove-vertex
    m1, m2 = graph.merge(a, b), graph.merge(b, a)
    assert_states_equal(m1, m2)
    assert not bool(graph.contains_edge(m1, 0, 1, 2))
    assert int(graph.edge_count(m1)[0]) == 0


def test_graph_merge_idempotent():
    a = graph.init(1, 8, 8)
    a = _g(a, graph.OP_ADD_VERTEX, a0=1)
    a = _g(a, graph.OP_ADD_VERTEX, a0=2)
    a = _g(a, graph.OP_ADD_EDGE, a0=1, a1=2)
    assert_states_equal(graph.merge(a, a), a)


# ---------------------------------------------------------------------------
# Canonical form: fresh and merged states are bit-equal (regression — the
# init fill and the slot_union output fill must agree, or state digests
# report spurious divergence).
# ---------------------------------------------------------------------------

def test_merge_is_bitwise_idempotent_from_init():
    a = orset.init(1, 8)
    a = _orset_add(a, 0, 7, 0, 1)
    assert_states_equal(orset.merge(a, a), a)
    l = lwwset.init(1, 8)
    l = _lww(l, lwwset.OP_ADD, 0, 5, 0, 10)
    assert_states_equal(lwwset.merge(l, l), l)
    t = tpset.init(1, 8)
    t = _tp(t, tpset.OP_ADD, 0, 5)
    assert_states_equal(tpset.merge(t, t), t)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

def test_registry_has_all_types():
    codes = set(base.registered_types())
    assert {"pnc", "orset", "lww", "tpset", "mvr", "graph"} <= codes
