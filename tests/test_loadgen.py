"""Native load generator end-to-end: loadgen.cc drives a real service
over loopback TCP and every op gets exactly one reply with a sane
latency stamp (the wire benchmark's load side — reference
BenchmarkRunners.cs:32-284 shape, native because the Python client caps
at ~25k ops/s process-wide and would measure the driver)."""
import numpy as np

from janus_tpu.net import JanusClient, JanusConfig, JanusService, TypeConfig
from janus_tpu.net.binding import NativeServer


def test_loadgen_closed_loop_roundtrip():
    svc = JanusService(JanusConfig(
        num_nodes=4, window=8, ops_per_block=32, max_clients=8,
        types=(TypeConfig("pnc", {"num_keys": 16}),)))
    port = svc.start()
    try:
        pre = JanusClient("127.0.0.1", port, timeout=120)
        for k in range(4):
            assert pre.request("pnc", f"o{k}", "s",
                               timeout=120)["result"] == "success"
        elapsed, counts, lat, cls = NativeServer.loadgen_run(
            "127.0.0.1", port, conns=2, ops_per_conn=120, pipeline=16,
            n_keys=4, type_code="pnc", pct_get=30, pct_upd=60, seed=3)
        # every op replied exactly once, classes partition the total
        assert sum(counts) == 2 * 120
        assert len(lat) == len(cls) == 2 * 120
        assert counts[2] > 0, "no safe updates in a 10% safe mix"
        for i in range(3):
            assert counts[i] == int((cls == i).sum())
        # latency stamps are positive and bounded by the run's wall time
        assert (lat > 0).all()
        assert float(lat.max()) <= elapsed * 1e3 + 1
        # safe updates wait for consensus: their median exceeds the
        # immediate-reply update median
        assert (np.median(lat[cls == 2]) > np.median(lat[cls == 1]))
        # the server agrees on the volume (creates + warmupless run)
        stats = pre.request("stats", "_", "g", timeout=120)
        assert '"ops_received"' in stats["result"]
        pre.close()
    finally:
        svc.stop()
