"""Latency anatomy + cluster-wide causal trace plane (ISSUE 18).

Contracts under test:

- ``make_trace_id`` mints nonzero compact ids embedding pid / sender /
  seq0, and the v3 batch-frame header round-trips one end to end: a
  traced frame's ops surface the id through the native ring (or the
  Python router) into the worker's flight lane ``x{id:x}``;
- wire-version interop on one service: v1 (no t0, no trace), v2 (t0,
  no trace) and v3 (t0 + trace) frames apply their ops identically
  while the ledger's ``unstamped`` / ``untraced`` counters attribute
  exactly which generation sent what — native demux and Python router
  agree;
- ``anatomy_report`` decomposes a run window's e2e p50 into segment
  p50s with coverage ratios computed from bucket-count DELTAS;
- ``merged_chrome_trace_events`` puts every node on its own Perfetto
  pid and shifts each node's timestamps by its clock offset;
- the obs endpoint's ``?n=`` query caps /flight and /trace dumps
  (newest-first), /flight carries the peer clock (``now_ns``) the
  federation's offset estimate needs, and /trace self-accounts its
  render CPU;
- ``fold_bench_trend`` folds BENCH_r*.json + results_r*.jsonl into one
  markdown trend table and tolerates gaps/broken artifacts.
"""
import importlib.util
import json
import pathlib
import socket
import time
import urllib.request

import numpy as np
import pytest

from janus_tpu.net import JanusClient, JanusConfig, JanusService, TypeConfig
from janus_tpu.net.client import encode_batch_frame, frame0, make_trace_id
from janus_tpu.obs import flight
from janus_tpu.obs.metrics import Registry, get_registry
from janus_tpu.obs.slo import SloLedger
from janus_tpu.obs.traceview import merged_chrome_trace_events, span_chains

KEYS = [f"o{k}" for k in range(4)]


# -- trace-id minting ------------------------------------------------------


def test_make_trace_id_nonzero_and_field_layout():
    import os

    tid = make_trace_id(7, 0x12345678)
    assert tid != 0  # zero is the "untraced" sentinel on the wire
    assert tid & 0xFFFFFFFF == 0x12345678
    assert (tid >> 32) & 0xFF == 7
    assert (tid >> 40) & 0xFFFFFF == os.getpid() & 0xFFFFFF
    # seq0 = 0 with sender 0 must still be nonzero (the pid field)
    assert make_trace_id(0, 0) != 0


# -- v1/v2/v3 wire interop through a live sharded service ------------------


def _frame(version: int, seq0: int, keys, idx, p0, trace_id: int = 0):
    """Encode one increments frame at the given wire version. v2/v3 use
    the client encoder; v1 is hand-built (pre-t0 header layout)."""
    import struct

    m = len(idx)
    if version >= 2:
        return encode_batch_frame(
            seq0, "pnc", keys, np.asarray(idx, np.int32),
            np.full(m, ord("i"), np.uint8), np.zeros(m, np.uint8),
            np.asarray(p0, np.int64),
            t0_ns=time.monotonic_ns(),
            trace_id=trace_id if version >= 3 else 0)
    tc = b"pnc"
    head = bytearray([0x00, 1, len(tc)])
    head += tc
    head += struct.pack("<I", seq0 & 0xFFFFFFFF)
    head += struct.pack("<H", len(keys))
    for k in keys:
        kb = k.encode()
        head += struct.pack("<H", len(kb)) + kb
    head += struct.pack("<I", m)
    head += np.asarray(idx, np.int32).tobytes()
    head += np.full(m, ord("i"), np.uint8).tobytes()
    head += np.zeros(m, np.uint8).tobytes()
    head += np.asarray(p0, np.int64).tobytes()
    return bytes(head)


@pytest.mark.usefixtures("native_lib")
@pytest.mark.parametrize("native", [True, False],
                         ids=["native_demux", "pyrouter"])
def test_frame_version_interop_counts_unstamped_untraced(native):
    get_registry().reset()
    rec = flight.enable()
    rec.clear()
    svc = JanusService(JanusConfig(
        num_nodes=4, window=8, ops_per_block=16, shards=2,
        native_demux=native,
        types=(TypeConfig("pnc", {"num_keys": 16}),)))
    port = svc.start()
    m = 32
    idx = [i % 4 for i in range(m)]
    p0 = [1] * m
    tid3 = make_trace_id(9, 2 * m + 1)
    try:
        with JanusClient("127.0.0.1", port, timeout=120) as c:
            for k in KEYS:
                assert c.request("pnc", k, "s",
                                 timeout=120)["response"] != "err"
            base = svc._slo_snapshot()
            with socket.create_connection(("127.0.0.1", port),
                                          timeout=30) as sk:
                sk.sendall(frame0(_frame(1, 1, KEYS, idx, p0)))
                sk.sendall(frame0(_frame(2, m + 1, KEYS, idx, p0)))
                sk.sendall(frame0(_frame(3, 2 * m + 1, KEYS, idx, p0,
                                         trace_id=tid3)))
                deadline = time.time() + 120
                snap = svc._slo_snapshot()
                while (snap["replied_total"]
                       < base["replied_total"] + 3 * m
                       and time.time() < deadline):
                    time.sleep(0.05)
                    snap = svc._slo_snapshot()
            # counter attribution: only the v1 frame is unstamped; the
            # v1 AND v2 frames are untraced; the v3 frame is both
            # stamped and traced, so it moves neither counter
            assert (snap["unstamped"] - base["unstamped"]) == m
            assert (snap["untraced"] - base["untraced"]) == 2 * m
            # e2e sampling saw the two stamped frames only
            d_samples = (snap["classes"]["unsafe"]["e2e_samples"]
                         - base["classes"]["unsafe"]["e2e_samples"])
            assert d_samples == 2 * m
            # all three generations applied: each key took 3m/4 ops x 1
            deadline = time.time() + 120
            while time.time() < deadline:
                got = {k: int(c.request("pnc", k, "gp",
                                        timeout=120)["result"])
                       for k in KEYS}
                if all(v == 3 * m // 4 for v in got.values()):
                    break
                time.sleep(0.1)
            assert all(v == 3 * m // 4 for v in got.values()), got
        # the v3 trace id owns a flight lane: the wire id is the lane
        # name end to end (ring/combine handoff + pipeline spans land
        # on it; which spans depends on the demux arm, but the lane
        # itself must exist and carry at least one span)
        chains = span_chains(rec.snapshot())
        assert f"x{tid3:x}" in chains, sorted(chains)[:10]
        assert len(chains[f"x{tid3:x}"]) >= 1
    finally:
        flight.disable()
        svc.stop()


# -- anatomy_report --------------------------------------------------------


def test_anatomy_report_decomposes_e2e_from_deltas():
    from janus_tpu.bench.harness import anatomy_report

    reg = Registry()
    led = SloLedger(registry=reg)
    slo0 = led.snapshot()
    now = 1_000_000
    # 100 unsafe ops at e2e 8 us, split 2/2/4 us across wire/ring/reply
    # (each leg's power-of-2 bucket midpoint sums exactly to the e2e
    # bucket's midpoint, so quantization cancels and coverage is 1.0)
    led.observe_batch("unsafe", np.full(100, now - 8_000, np.int64),
                      now_ns=now)
    led.observe_seg("unsafe", "wire", np.full(100, 2_000, np.int64))
    led.observe_seg("unsafe", "ring", np.full(100, 2_000, np.int64))
    led.observe_seg("unsafe", "reply", np.full(100, 4_000, np.int64))
    rep = anatomy_report(slo0, led.snapshot())
    d = rep["unsafe"]
    assert d["e2e_samples"] == 100
    assert set(d["segments"]) == {"wire", "ring", "reply"}
    assert d["segments"]["reply"]["samples"] == 100
    # exact sums: 2+3+5 us accounts for all 10 us
    assert d["coverage_ns"] == pytest.approx(1.0, abs=0.01)
    # p50 coverage is quantized by the power-of-2 buckets but must
    # still clear the smoke gate's one-sided 95% bound
    assert d["coverage_p50"] >= 0.95
    # classes that saw no traffic are absent, not zero-filled
    assert "safe" not in rep and "stable" not in rep
    assert rep["unstamped"] == 0 and rep["untraced"] == 0


def test_anatomy_report_windows_out_prior_traffic():
    from janus_tpu.bench.harness import anatomy_report

    led = SloLedger(registry=Registry())
    # pre-window noise: slow ops that must NOT leak into the report
    led.observe_batch("unsafe", np.full(50, 0 - 0, np.int64))  # unstamped
    led.observe_batch("unsafe", np.full(7, 1_000, np.int64),
                      now_ns=90_000_000)
    led.observe_seg("unsafe", "reply", np.full(7, 89_000_000, np.int64))
    slo0 = led.snapshot()
    led.observe_batch("unsafe", np.full(20, 2_000, np.int64),
                      now_ns=10_000)
    led.observe_seg("unsafe", "reply", np.full(20, 8_000, np.int64))
    rep = anatomy_report(slo0, led.snapshot())
    d = rep["unsafe"]
    assert d["e2e_samples"] == 20
    assert d["segments"]["reply"]["samples"] == 20
    # the window's p50 reflects the 8 us ops, not the 89 ms noise
    assert d["e2e_p50_ms"] < 1.0


# -- merged Perfetto export ------------------------------------------------


def test_merged_chrome_trace_events_shifts_and_separates_pids():
    ev_a = [(1_000_000, "x1", "ring", "S", 500),
            (1_002_000, "x1", "ingest", "S", 200)]
    ev_b = [(2_000_000, "x1", "seal", "S", 100),
            (2_001_000, "c7", "combine_absorbed", "I", 32)]
    out = merged_chrome_trace_events([("h0", 0, ev_a),
                                      ("h1", -500_000, ev_b)])
    names = {e["args"]["name"]: e["pid"] for e in out
             if e.get("name") == "process_name"}
    assert set(names) == {"h0", "h1"}
    assert names["h0"] != names["h1"]
    by = {(e["pid"], e["name"]): e for e in out if e["ph"] in ("X", "i")}
    # h0 unshifted; h1 shifted onto the merger's clock by its offset
    assert by[(names["h0"], "ring")]["ts"] == pytest.approx(1_000_000 / 1e3)
    assert by[(names["h1"], "seal")]["ts"] == pytest.approx(
        (2_000_000 - 500_000) / 1e3)
    # instants keep their detail payload
    assert by[(names["h1"], "combine_absorbed")]["args"]["detail"] == 32
    # the same trace id on two nodes stays two lanes under two pids —
    # cross-process correlation is by lane NAME at aligned time
    name_meta = [e for e in out if e.get("name") == "thread_name"
                 and e["args"]["name"] == "x1"]
    assert len(name_meta) == 2
    assert len({e["pid"] for e in name_meta}) == 2


# -- obs endpoint: /flight + capped /trace ---------------------------------


def test_flight_endpoint_serves_clock_and_caps_dump():
    get_registry().reset()
    rec = flight.enable()
    rec.clear()
    svc = JanusService(JanusConfig(
        num_nodes=4, window=8, ops_per_block=16, shards=1, obs_port=0,
        types=(TypeConfig("pnc", {"num_keys": 16}),)))
    port = svc.start()
    base = f"http://127.0.0.1:{svc.obs_port}"
    try:
        with JanusClient("127.0.0.1", port, timeout=120) as c:
            assert c.request("pnc", "o0", "s",
                             timeout=120)["response"] != "err"
            for _ in range(8):
                seq = c.send("pnc", "o0", "i", ["1"])
            c.wait(seq, timeout=120)
        doc = json.loads(urllib.request.urlopen(
            base + "/flight", timeout=30).read())
        assert doc["total"] > 0 and len(doc["events"]) > 0
        # now_ns is the peer-clock sample the federation's offset
        # estimate brackets between its send/recv stamps
        assert abs(doc["now_ns"] - time.time_ns()) < 120 * 1_000_000_000
        capped = json.loads(urllib.request.urlopen(
            base + "/flight?n=3", timeout=30).read())
        assert len(capped["events"]) == 3
        # newest-first suffix: the cap keeps the latest events
        assert capped["events"] == doc["events"][-3:] or \
            capped["events"][-1][0] >= doc["events"][0][0]
        tr = json.loads(urllib.request.urlopen(
            base + "/trace?n=4", timeout=30).read())
        lanes = {e["tid"] for e in tr["traceEvents"]
                 if e.get("ph") in ("X", "i")}
        assert 0 < len(tr["traceEvents"]) and len(lanes) >= 1
        # the render self-accounts its CPU instead of hiding in the
        # goodput numbers
        assert get_registry().counter("obs_trace_cpu_ns").value > 0
    finally:
        flight.disable()
        svc.stop()


# -- fold_bench_trend ------------------------------------------------------


def _load_trend_module():
    path = (pathlib.Path(__file__).resolve().parent.parent
            / "scripts" / "fold_bench_trend.py")
    spec = importlib.util.spec_from_file_location("fold_bench_trend", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fold_bench_trend_merges_both_artifact_kinds(tmp_path):
    mod = _load_trend_module()
    (tmp_path / "BENCH_r01.json").write_text(json.dumps({
        "n": 1, "rc": 0,
        "parsed": {"metric": "pnc_ops", "value": 1_000_000.0,
                   "unit": "ops/s", "vs_baseline": 4.0,
                   "consensus": {"safe_ops_per_sec": 50_000.0,
                                 "p50_ms": 12.5}}}))
    rows = [
        {"run": "w", "mode": "wire_sharded",
         "throughput_ops_per_sec": 2_000_000.0},
        {"run": "w2", "mode": "wire_native",
         "throughput_ops_per_sec": 1_500_000.0},
        {"run": "mh", "aggregate_goodput_ops_per_sec": 3_000_000.0},
    ]
    (tmp_path / "results_r2.jsonl").write_text(
        "\n".join(json.dumps(r) for r in rows) + "\nnot json\n")
    (tmp_path / "BENCH_r03.json").write_text("{broken")  # skipped
    trend = mod.fold_trend(str(tmp_path))
    assert set(trend) == {1, 2}
    assert trend[1]["fastpath_ops_per_sec"] == 1_000_000.0
    assert trend[1]["safe_p50_ms"] == 12.5
    assert trend[2]["wire_goodput_ops_per_sec"] == 2_000_000.0
    assert trend[2]["multihost_goodput_ops_per_sec"] == 3_000_000.0
    text = mod.render_markdown(trend)
    assert "| r01 |" in text and "| r02 |" in text
    assert "1,000,000" in text and "3,000,000" in text
    # a round with no wire rows renders "-", not a dropped row
    assert text.count("| r0") == 2


def test_fold_bench_trend_on_the_real_repo_artifacts():
    """The repo root's own BENCH_r*/results_r* evidence must fold into
    a non-degenerate table — this is the satellite's tier-1 smoke."""
    mod = _load_trend_module()
    root = str(pathlib.Path(__file__).resolve().parent.parent)
    trend = mod.fold_trend(root)
    assert len(trend) >= 5
    assert any("fastpath_ops_per_sec" in r for r in trend.values())
    assert any("wire_goodput_ops_per_sec" in r for r in trend.values())
    text = mod.render_markdown(trend)
    assert text.startswith("# Bench trend")
    for rnd in sorted(trend):
        assert f"| r{rnd:02d} |" in text


def test_fold_bench_trend_empty_dir_is_graceful(tmp_path):
    mod = _load_trend_module()
    assert mod.fold_trend(str(tmp_path)) == {}
    assert "no BENCH_r" in mod.render_markdown({})


# -- query_route plumbing --------------------------------------------------


def test_query_route_parses_params_last_value_wins():
    from janus_tpu.obs.httpexp import ObsHttpServer, query_route, scrape_text

    @query_route
    def echo(q):
        return "application/json", json.dumps(q)

    srv = ObsHttpServer({"/echo": echo,
                         "/plain": lambda: ("text/plain", "ok")},
                        registry=Registry())
    base = f"http://127.0.0.1:{srv.port}"
    try:
        assert json.loads(scrape_text(base + "/echo")) == {}
        got = json.loads(scrape_text(base + "/echo?a=1&b=&a=2"))
        assert got == {"a": "2", "b": ""}
        # non-query routes ignore a stray query string
        assert scrape_text(base + "/plain?x=1") == "ok"
    finally:
        srv.close()
