"""Delta-state convergence: the union-dirty slab converge must be
BIT-EXACT with the full tree-reduce converge — it is an optimization of
the anti-entropy round, never a semantic change. Property-tested for
ORSet and PNCounter over random op streams, including the counted
``lax.cond`` fallback when the dirty count overflows the slab budget,
plus the Store-level plumbing (sync_delta / sync_all / fused_tick and
its recompile guard).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from janus_tpu.models import base, orset, pncounter
from janus_tpu.runtime.store import (
    Store, apply_replica_ops, apply_replica_ops_delta, converge,
    converge_delta, replicated_init)
from janus_tpu.utils.ids import TagMinter

R, B, K = 4, 8, 32


def _pnc_stream(rng, ticks, noop_frac=0.2):
    out = []
    writer = np.broadcast_to(np.arange(R, dtype=np.int32)[:, None], (R, B))
    for _ in range(ticks):
        op = rng.integers(pncounter.OP_INC, pncounter.OP_DEC + 1, (R, B))
        op = np.where(rng.random((R, B)) < noop_frac, base.OP_NOOP, op)
        out.append(base.make_op_batch(
            op=op.astype(np.int32),
            key=rng.integers(0, K, (R, B)).astype(np.int32),
            a0=rng.integers(1, 10, (R, B)), writer=writer))
    return out


def _orset_stream(rng, ticks, minters, noop_frac=0.2):
    out = []
    for _ in range(ticks):
        is_add = rng.random((R, B)) < 0.6
        tags = np.zeros((R, B, 2), np.int32)
        for v in range(R):
            lanes = np.nonzero(is_add[v])[0]
            if lanes.size:
                tags[v, lanes] = minters[v].mint_many(lanes.size)
        op = np.where(is_add, orset.OP_ADD, orset.OP_REMOVE)
        op = np.where(rng.random((R, B)) < noop_frac, base.OP_NOOP, op)
        out.append(base.make_op_batch(
            op=op.astype(np.int32),
            key=rng.integers(0, K, (R, B)).astype(np.int32),
            a0=rng.integers(0, 16, (R, B)),
            a1=tags[..., 0], a2=tags[..., 1]))
    return out


def _streams(seed, ticks=6):
    rng = np.random.default_rng(seed)
    minters = [TagMinter(v) for v in range(R)]
    return {
        "pnc": (pncounter.SPEC,
                replicated_init(pncounter.SPEC, R, num_keys=K, num_writers=R),
                _pnc_stream(rng, ticks)),
        "orset": (orset.SPEC,
                  replicated_init(orset.SPEC, R, num_keys=K, capacity=64,
                                  rm_capacity=4),
                  _orset_stream(rng, ticks, minters)),
    }


def _assert_trees_equal(a, b, msg):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb),
                                      err_msg=msg)


# jitted per (type, budget) ONCE for the whole module — the production
# paths are jitted too, and eager slot-union chains are minutes-slow
_TICKS = {}


def _full_tick(tc, spec):
    if ("full", tc) not in _TICKS:
        _TICKS[("full", tc)] = jax.jit(
            lambda s, o: converge(spec, apply_replica_ops(spec, s, o)))
    return _TICKS[("full", tc)]


def _delta_tick(tc, spec, budget):
    key = ("delta", tc, budget)
    if key not in _TICKS:
        def tick(s, o):
            st, dirty, dropped = apply_replica_ops_delta(spec, s, o)
            st, ovf, count = converge_delta(spec, st, dirty, budget)
            return st, dirty, ovf, count
        _TICKS[key] = jax.jit(tick)
    return _TICKS[key]


@pytest.mark.parametrize("tc", ["pnc", "orset"])
@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize("budget", [2, K])
def test_delta_converge_bitexact(tc, seed, budget):
    """Full apply+converge vs delta apply+slab-converge over the same
    random op stream: bit-identical states every tick. budget=2 forces
    the overflow fallback (B random keys per replica dirty >> 2);
    budget=K can never overflow (count <= K)."""
    spec, state0, stream = _streams(seed)[tc]
    full = state0
    delta = state0
    overflows = 0
    for ops in stream:
        full = _full_tick(tc, spec)(full, ops)
        delta, dirty, ovf, count = _delta_tick(tc, spec, budget)(delta, ops)
        # the dirty mask is exactly the keys of enabled ops
        want = np.zeros((R, K), bool)
        opv = np.asarray(ops["op"])
        keyv = np.asarray(ops["key"])
        for r in range(R):
            want[r, keyv[r][opv[r] != base.OP_NOOP]] = True
        np.testing.assert_array_equal(np.asarray(dirty), want)
        overflows += int(ovf)
        assert int(count) == int(want.any(axis=0).sum())
        _assert_trees_equal(full, delta, f"{tc} diverged (budget={budget})")
    if budget == 2:
        assert overflows == len(stream)  # every tick fell back, counted
    else:
        assert overflows == 0


@pytest.mark.parametrize("tc", ["pnc", "orset"])
def test_delta_apply_matches_plain_apply(tc):
    """apply_ops_delta's state output is the plain apply_ops state."""
    spec, state, stream = _streams(7)[tc]
    ap = jax.jit(lambda s, o: apply_replica_ops(spec, s, o))
    apd = jax.jit(lambda s, o: apply_replica_ops_delta(spec, s, o)[0])
    for ops in stream:
        plain = ap(state, ops)
        tracked = apd(state, ops)
        _assert_trees_equal(plain, tracked, f"{tc} apply_ops_delta != apply_ops")
        state = _full_tick(tc, spec)(state, ops)


def _types():
    return {"pnc": dict(num_keys=K, num_writers=R),
            "orset": dict(num_keys=K, capacity=64, rm_capacity=4)}


def _apply_all(store, ops_by_type):
    for tc, ops in ops_by_type.items():
        store.apply(tc, ops)


def test_store_sync_delta_matches_sync():
    _, _, pnc_stream = _streams(11)["pnc"]
    _, _, or_stream = _streams(11)["orset"]
    a = Store(R, _types())
    b = Store(R, _types(), dirty_budget=K // 2)
    for pops, oops in zip(pnc_stream, or_stream):
        batch = {"pnc": pops, "orset": oops}
        _apply_all(a, batch)
        _apply_all(b, batch)
        a.sync("pnc"), a.sync("orset")
        b.sync_delta("pnc"), b.sync_delta("orset")
        for tc in ("pnc", "orset"):
            _assert_trees_equal(a.states[tc], b.states[tc],
                                f"sync_delta diverged on {tc}")
            assert not bool(np.asarray(b.dirty[tc]).any())


def test_store_sync_all_matches_per_type_sync():
    _, _, pnc_stream = _streams(13)["pnc"]
    _, _, or_stream = _streams(13)["orset"]
    a = Store(R, _types())
    b = Store(R, _types())
    for pops, oops in zip(pnc_stream, or_stream):
        batch = {"pnc": pops, "orset": oops}
        _apply_all(a, batch)
        _apply_all(b, batch)
        a.sync("pnc"), a.sync("orset")
        b.sync_all()
        for tc in ("pnc", "orset"):
            _assert_trees_equal(a.states[tc], b.states[tc],
                                f"sync_all diverged on {tc}")


@pytest.mark.parametrize("budget,expect_overflow", [(K, False), (2, True)])
def test_store_fused_tick_bitexact_and_compiles_once(budget, expect_overflow):
    """>= 3 fused two-type megaticks: bit-exact vs the unfused reference
    path, ONE trace total (the recompile guard — a retrace per tick
    would hand the megatick's dispatch win straight back to the
    compiler), one dispatch per tick."""
    ticks = 4
    rng = np.random.default_rng(17)
    minters = [TagMinter(v) for v in range(R)]
    pnc_stream = _pnc_stream(rng, ticks)
    or_stream = _orset_stream(rng, ticks, minters)
    ref = Store(R, _types())
    fused = Store(R, _types(), dirty_budget=budget)
    for pops, oops in zip(pnc_stream, or_stream):
        batch = {"pnc": pops, "orset": oops}
        _apply_all(ref, batch)
        ref.sync("pnc"), ref.sync("orset")
        fused.fused_tick(batch)
        for tc in ("pnc", "orset"):
            _assert_trees_equal(ref.states[tc], fused.states[tc],
                                f"fused_tick diverged on {tc}")
    assert fused.fused_trace_count == 1
    assert fused.fused_dispatch_count == ticks
    overflowed = {tc: n for tc, n in (
        (tc, int(fused._fused_acc[f"overflow_{tc}"]))
        for tc in ("pnc", "orset"))}
    if expect_overflow:
        assert all(n == ticks for n in overflowed.values())
    else:
        assert all(n == 0 for n in overflowed.values())
    fracs = fused.flush_metrics()
    assert set(fracs) == {"pnc", "orset"}
    assert all(0.0 < f <= 1.0 for f in fracs.values())


def test_converge_delta_zero_dirty_is_noop():
    """An all-clean mask leaves the state untouched (and cheap)."""
    spec, state, stream = _streams(23)["orset"]
    state = _full_tick("orset", spec)(state, stream[0])
    out, ovf, count = jax.jit(
        lambda s, d: converge_delta(spec, s, d, 4))(
            state, jnp.zeros((R, K), bool))
    assert not bool(ovf) and int(count) == 0
    _assert_trees_equal(state, out, "clean converge_delta mutated state")
