"""Split-cluster deployment tests: payload-carrying signed blocks across
process-shaped endpoints (reference: one OS process per replica wired by
Cluster/CMNode TCP, DAGMessage.cs:68-114 blocks-carry-updates,
Block.cs:45-88 digests/signatures, DAG.cs:612-621 block-query repair).

Endpoints here exchange REAL serialized frames; the transports are
in-memory pipes (deterministic) and loopback TCP (the wire shape).
"""
import threading
import time

import numpy as np
import pytest

from janus_tpu.consensus.dag import DagConfig
from janus_tpu.models import base, orset, pncounter
from janus_tpu.net.dagplane import TcpPeer
from janus_tpu.net.splitnode import SplitNode

N, W, B = 4, 8, 2
K = 4


def _pnc_ops(nodes, amount=5):
    op = np.zeros((N, B), np.int32)
    for v in nodes:
        op[v, :] = pncounter.OP_INC
    return base.make_op_batch(
        op=op, key=np.zeros((N, B), np.int32),
        a0=np.full((N, B), amount, np.int32),
        writer=np.broadcast_to(np.arange(N, dtype=np.int32)[:, None],
                               (N, B)).copy())


class _Pipes:
    """In-memory broadcast fabric between endpoints, with an optional
    per-sender corruption hook."""

    def __init__(self, count, corrupt=None):
        self.boxes = [[] for _ in range(count)]
        self.corrupt = corrupt or {}

    def sender(self, idx):
        def send(data: bytes):
            fn = self.corrupt.get(idx)
            payload = fn(data) if fn else data
            for j, box in enumerate(self.boxes):
                if j != idx:
                    box.append(payload)
        return send

    def pump(self, nodes):
        moved = True
        while moved:
            moved = False
            for j, node in enumerate(nodes):
                if self.boxes[j]:
                    moved = True
                    for d in self.boxes[j]:
                        node.receive(d)
                    self.boxes[j].clear()


def _mk(owned, send, spec=pncounter.SPEC, **dims):
    if not dims:
        dims = {"num_keys": K, "num_writers": N}
    return SplitNode(DagConfig(N, W), spec, B, owned, send=send, **dims)


def test_two_process_payload_replication():
    """VERDICT round-3 item 2: an op submitted at process A must read
    back from process B's STABLE state — blocks carry their payloads."""
    pipes = _Pipes(2)
    a = _mk([1, 1, 0, 0], pipes.sender(0))
    b = _mk([0, 0, 1, 1], pipes.sender(1))
    nodes = [a, b]
    a.start(); b.start(); pipes.pump(nodes)

    safe = np.zeros((N, B), bool)
    safe[0] = True
    # first step completes the key exchange (inits drain inside step)
    a.step(); pipes.pump(nodes)
    b.step(); pipes.pump(nodes)
    assert a.ready and b.ready

    # submit with retry: a slot can be sealed by an earlier idle round
    # (the service requeues on a False accept bit the same way)
    acked = False
    boarded = False
    for t in range(30):
        info = a.step(None if boarded else _pnc_ops([0, 1]),
                      safe=None if boarded else safe)
        boarded = boarded or (info is not None
                              and bool(info["accepted"][:2].all()))
        pipes.pump(nodes)
        b.step()
        pipes.pump(nodes)
        acked = acked or a.kv.safe_acks()[:, 0, :].any()
    assert boarded
    # both of A's nodes incremented key 0 by 5, twice (B lanes)
    expect = 2 * B * 5
    a_stable = np.asarray(a.query_stable("get"))[:2, 0]
    b_stable = np.asarray(b.query_stable("get"))[2:, 0]
    np.testing.assert_array_equal(a_stable, expect)
    np.testing.assert_array_equal(b_stable, expect)
    # A's safe ops were acked at commit (the deferred-reply signal)
    assert acked
    # every frame verified, nothing dropped, GC advanced on both sides
    for n_ in nodes:
        assert n_.stats["verified_bad"] == 0
        assert n_.kv.base_round() > 2
    # committed total orders agree across processes (prefix equality)
    oa = a.kv.ordered_commits(0)
    ob = b.kv.ordered_commits(2)
    common = min(len(oa), len(ob))
    assert common > 10
    assert oa[:common] == ob[:common]


def test_orset_capture_payload_across_processes():
    """Effect-captured ops (OR-Set removes carry observed tags) must
    survive serialization: an add+remove at A leaves B's stable empty."""
    dims = {"num_keys": 2, "capacity": 16, "rm_capacity": 4}
    pipes = _Pipes(2)
    a = _mk([1, 1, 0, 0], pipes.sender(0), orset.SPEC, **dims)
    b = _mk([0, 0, 1, 1], pipes.sender(1), orset.SPEC, **dims)
    nodes = [a, b]
    a.start(); b.start(); pipes.pump(nodes)

    def drive(ops=None):
        a.step(ops)
        pipes.pump(nodes)
        b.step()
        pipes.pump(nodes)

    def drive_until_boarded(ops, node_idx=0):
        for _ in range(10):
            info = a.step(ops)
            pipes.pump(nodes)
            b.step()
            pipes.pump(nodes)
            if info is not None and info["accepted"][node_idx]:
                return
        raise AssertionError("ops never boarded a block")

    add = base.make_op_batch(
        op=np.asarray([[orset.OP_ADD, 0]] + [[0, 0]] * 3, np.int32),
        key=np.zeros((N, B), np.int32),
        a0=np.full((N, B), 42, np.int32),
        a1=np.zeros((N, B), np.int32),
        a2=np.asarray([[1, 0]] + [[0, 0]] * 3, np.int32),
        writer=np.broadcast_to(np.arange(N, dtype=np.int32)[:, None],
                               (N, B)).copy())
    drive_until_boarded(add)
    for _ in range(14):
        drive()
    # the add crossed: B sees 42 in its prospective/stable
    assert bool(np.asarray(b.query_stable("contains", 0, 42))[2])
    rm = base.make_op_batch(
        op=np.asarray([[orset.OP_REMOVE, 0]] + [[0, 0]] * 3, np.int32),
        key=np.zeros((N, B), np.int32),
        a0=np.full((N, B), 42, np.int32),
        writer=np.broadcast_to(np.arange(N, dtype=np.int32)[:, None],
                               (N, B)).copy())
    drive_until_boarded(rm)
    for _ in range(14):
        drive()
    got = np.asarray(b.query_stable("contains", 0, 42))[2:]
    assert not got.any(), "captured remove did not replicate"


def test_tampered_blocks_dropped_liveness_holds():
    """VERDICT round-3 item 7: a peer whose block frames are corrupted
    in transit is detected (signature verification) and excluded; the
    honest 2f+1 keep committing."""

    def flip(data: bytes) -> bytes:
        # corrupt one byte well inside every frame (hits edges/ops
        # payload bytes; the signature then fails everywhere honest)
        mut = bytearray(data)
        if len(mut) > 24:
            mut[20] ^= 0xFF
        return bytes(mut)

    pipes = _Pipes(4, corrupt={3: flip})
    nodes = [_mk([i == j for j in range(N)], pipes.sender(i))
             for i in range(N)]
    for n_ in nodes:
        n_.start()
    pipes.pump(nodes)
    boarded = [False] * N
    for t in range(60):
        for i, n_ in enumerate(nodes):
            info = n_.step(None if boarded[i] else _pnc_ops([i]))
            if not boarded[i] and info is not None:
                boarded[i] = bool(info["accepted"][i])
        pipes.pump(nodes)
    assert all(boarded)

    honest = nodes[:3]
    # honest nodes detected the corruption and kept advancing: a round
    # takes ~4 step+pump exchanges across 4 endpoints, so 60 iterations
    # reach ~14 rounds — past the W=8 window, which proves the GC
    # frontier moves (the ring would deadlock rounds at W-1 otherwise)
    assert any(n_.stats["verified_bad"] > 0 for n_ in honest)
    for n_ in honest:
        assert int(np.asarray(n_.kv.dag["node_round"])[n_.owned_idx[0]]) > 10
    # node 3's blocks never commit in honest views (they never certify)
    for n_ in honest:
        v = int(n_.owned_idx[0])
        assert all(src != 3 for _r, src in n_.kv.ordered_commits(v))
    # honest ops still committed and replicated everywhere honest
    for n_ in honest:
        vals = np.asarray(n_.query_stable("get"))[n_.owned_idx[0], 0]
        assert int(vals) == 3 * B * 5  # nodes 0..2 each +5 per lane


def test_split_over_loopback_tcp():
    """The same two-process exchange over real sockets (TcpPeer), the
    CMNode/ManagerServer wire shape."""
    import socket

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    peers = {}
    a = _mk([1, 1, 0, 0], lambda d: peers["a"].send(d))
    b = _mk([0, 0, 1, 1], lambda d: peers["b"].send(d))

    accepted = {}

    def accept():
        conn, _ = srv.accept()
        accepted["sock"] = conn

    th = threading.Thread(target=accept)
    th.start()
    peers["b"] = TcpPeer.connect("127.0.0.1", port, b.receive)
    th.join()
    peers["a"] = TcpPeer(accepted["sock"], a.receive)

    try:
        a.start(); b.start()
        deadline = time.monotonic() + 60
        while not (a.ready and b.ready):
            a.step(); b.step()
            if time.monotonic() > deadline:
                pytest.fail("key exchange did not complete")
            time.sleep(0.01)
        boarded = False
        for t in range(40):
            info = a.step(None if boarded else _pnc_ops([0, 1]))
            boarded = boarded or (info is not None
                                  and bool(info["accepted"][:2].all()))
            b.step()
            time.sleep(0.002)
        assert boarded
        expect = 2 * B * 5
        b_stable = np.asarray(b.query_stable("get"))[2:, 0]
        np.testing.assert_array_equal(b_stable, expect)
        assert b.stats["verified_bad"] == 0
    finally:
        peers["a"].close()
        peers["b"].close()
        srv.close()


def test_key_exchange_budget_degrades_then_recovers():
    """Bounded park-and-retry: a node whose peers never answer the key
    exchange must stop waiting silently once its retry budget blows —
    ``degraded_reason`` is the watchdog feed — and must clear the
    verdict the moment the exchange completes."""
    a = _mk([1, 1, 0, 0], lambda data: None, key_retry_budget=5,
            num_keys=K, num_writers=N)
    a.start()
    for _ in range(4):
        assert a.step() is None      # not ready: parked, under budget
    assert a.degraded_reason is None
    assert a.step() is None          # 5th not-ready step blows the budget
    assert a.degraded_reason is not None
    assert "key exchange" in a.degraded_reason
    assert "missing nodes" in a.degraded_reason
    # the verdict names the peers that never answered (nodes 2, 3)
    assert "2" in a.degraded_reason and "3" in a.degraded_reason
    # late peer: B's init frames complete A's exchange; the next step
    # clears the verdict and the node serves normally
    b = _mk([0, 0, 1, 1], a.receive)
    b.start()                        # broadcasts keys into a.receive
    a.step()
    assert a.ready
    assert a.degraded_reason is None


def test_parked_block_dropped_after_retry_budget():
    """A block whose creator key never arrives is re-parked at most
    ``key_retry_budget`` times, then dropped and counted — the park
    list must not grow forever on a broken or hostile peer."""
    pipes = _Pipes(2)
    a = _mk([1, 1, 0, 0], pipes.sender(0), key_retry_budget=3,
            num_keys=K, num_writers=N)
    b = _mk([0, 0, 1, 1], pipes.sender(1))
    nodes = [a, b]
    a.start(); b.start(); pipes.pump(nodes)
    a.step(); pipes.pump(nodes)
    b.step(); pipes.pump(nodes)
    assert a.ready
    # a block parked for a source whose key will NEVER arrive (no such
    # node): each ready step retries it once, ages it, then drops it
    a._pending_blocks.append([2, 9, b"\x00", 0])
    for _ in range(2):
        a.step()
        pipes.pump(nodes)
        b.step()
        pipes.pump(nodes)
    assert a._pending_blocks, "parked block dropped before its budget"
    assert a.stats["parked_dropped"] == 0
    a.step()
    assert a._pending_blocks == []
    assert a.stats["parked_dropped"] == 1
    # the node itself stays healthy: parking is bounded, not DEGRADED
    assert a.degraded_reason is None
