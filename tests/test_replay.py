"""Replay-safety property tests: every registered type's replicated
replay must converge under arbitrary delivery orders.

The reference sidesteps this by shipping full state snapshots and joining
them (ReplicationManager.cs:347-357 — merge is commutative by
construction). This framework ships *ops* inside consensus payloads
(SafeKV ops_buffer), so op application after effect capture must itself
be order-insensitive: for any captured batch, applying its ops in any
interleaving on any two replicas and joining must agree. The round-1
advisor found ORSet violating this; these tests pin the fix for every
type (Tests analog: MergeSharp.Tests per-type convergence suites).
"""
import numpy as np

from janus_tpu.models import base, graph, lwwset, mvregister, orset, tpset


def _split_ops(ops, idx):
    return {f: v[idx] for f, v in ops.items()}


def _apply_sequence(spec, init_state, prepared, order):
    """Apply single-op batches in the given order onto a fresh state."""
    st = init_state
    for i in order:
        one = {f: v[i : i + 1] for f, v in prepared.items()}
        st = spec.apply_ops(st, one)
    return st


def _assert_replay_commutes(spec, init_state, origin_state, ops, perms,
                            canon=None):
    """Capture ops against origin_state; apply them to fresh replicas in
    several orders; all pairwise joins must be bit-identical."""
    prepared = spec.prepare_ops(origin_state, ops)
    states = [
        _apply_sequence(spec, init_state, prepared, perm) for perm in perms
    ]
    joined = [spec.merge(s, states[0]) for s in states]
    if canon is not None:
        joined = [canon(s) for s in joined]
    for other in joined[1:]:
        for f in joined[0]:
            np.testing.assert_array_equal(
                np.asarray(joined[0][f]), np.asarray(other[f]),
                err_msg=f"{spec.name}: field {f} diverged across orders",
            )


def test_orset_replay_orders_converge():
    origin = orset.init(2, 8)
    origin = orset.apply_ops(origin, base.make_op_batch(
        op=[orset.OP_ADD, orset.OP_ADD], key=[0, 1], a0=[7, 9],
        a1=[0, 0], a2=[1, 2]))
    ops = base.make_op_batch(
        op=[orset.OP_ADD, orset.OP_REMOVE, orset.OP_CLEAR],
        key=[0, 0, 1], a0=[7, 7, 0], a1=[1, 0, 0], a2=[1, 0, 0])
    # fresh replicas that already hold the origin's adds in one case and
    # nothing in the other: both directions of "late delivery"
    _assert_replay_commutes(
        orset.SPEC, origin, origin, ops,
        perms=[(0, 1, 2), (2, 1, 0), (1, 0, 2)])
    _assert_replay_commutes(
        orset.SPEC, orset.init(2, 8), origin, ops,
        perms=[(0, 1, 2), (2, 1, 0), (1, 0, 2)])


def test_tpset_replay_orders_converge():
    origin = tpset.init(1, 8)
    origin = tpset.apply_ops(origin, base.make_op_batch(
        op=[tpset.OP_ADD], key=[0], a0=[5]))
    ops = base.make_op_batch(
        op=[tpset.OP_ADD, tpset.OP_REMOVE], key=[0, 0], a0=[6, 5])
    _assert_replay_commutes(
        tpset.SPEC, tpset.init(1, 8), origin, ops,
        perms=[(0, 1), (1, 0)])
    # the gated remove fires even on a replica that never saw the add
    prepared = tpset.SPEC.prepare_ops(origin, ops)
    fresh = _apply_sequence(tpset.SPEC, tpset.init(1, 8), prepared, [1])
    late = tpset.apply_ops(fresh, base.make_op_batch(
        op=[tpset.OP_ADD], key=[0], a0=[5]))
    assert not bool(tpset.contains(late, 0, 5))


def test_lwwset_replay_orders_converge():
    origin = lwwset.init(1, 8)
    origin = lwwset.apply_ops(origin, base.make_op_batch(
        op=[lwwset.OP_ADD], key=[0], a0=[5], a1=[1], a2=[10]))
    ops = base.make_op_batch(
        op=[lwwset.OP_ADD, lwwset.OP_REMOVE], key=[0, 0],
        a0=[6, 5], a1=[1, 1], a2=[20, 30])
    _assert_replay_commutes(
        lwwset.SPEC, lwwset.init(1, 8), origin, ops,
        perms=[(0, 1), (1, 0)])
    # remove-before-add delivery: stamps still land, LWW decides
    prepared = lwwset.SPEC.prepare_ops(origin, ops)
    fresh = _apply_sequence(lwwset.SPEC, lwwset.init(1, 8), prepared, [1])
    late = lwwset.apply_ops(fresh, base.make_op_batch(
        op=[lwwset.OP_ADD], key=[0], a0=[5], a1=[1], a2=[10]))
    assert not bool(lwwset.contains(late, 0, 5))  # rm stamp (1,30) wins


def test_mvregister_replay_orders_converge():
    origin = mvregister.init(1, num_writers=4, capacity=4)
    origin = mvregister.apply_ops(origin, base.make_op_batch(
        op=[mvregister.OP_WRITE], key=[0], a0=[100], writer=[0]))
    ops = base.make_op_batch(
        op=[mvregister.OP_WRITE, mvregister.OP_WRITE], key=[0, 0],
        a0=[200, 300], writer=[1, 2])
    _assert_replay_commutes(
        mvregister.SPEC, mvregister.init(1, 4, 4), origin, ops,
        perms=[(0, 1), (1, 0)])
    # both writes observed (100) but not each other -> concurrent pair
    prepared = mvregister.SPEC.prepare_ops(origin, ops)
    st = _apply_sequence(mvregister.SPEC, origin, prepared, [0, 1])
    assert int(mvregister.num_values(st)[0]) == 2


def test_mvregister_same_writer_batch_stays_ordered():
    """Through the runtime capture path (capture_and_apply), a later
    same-key write in one batch observes the earlier one: its clock
    strictly dominates, so only the last value survives."""
    origin = mvregister.init(1, num_writers=4, capacity=4)
    ops = base.make_op_batch(
        op=[mvregister.OP_WRITE, mvregister.OP_WRITE], key=[0, 0],
        a0=[1, 2], writer=[3, 3])
    st, prepared = base.capture_and_apply(mvregister.SPEC, origin, ops)
    assert prepared["wclock"][1, 3] == prepared["wclock"][0, 3] + 1
    vals, valid = mvregister.read(st, 0)
    live = set(np.asarray(vals)[np.asarray(valid)].tolist())
    assert live == {2}


def test_graph_replay_orders_converge():
    origin = graph.init(1, v_capacity=8, e_capacity=8)
    origin = graph.apply_ops(origin, base.make_op_batch(
        op=[graph.OP_ADD_VERTEX, graph.OP_ADD_VERTEX, graph.OP_ADD_EDGE],
        key=[0, 0, 0], a0=[1, 2, 1], a1=[0, 0, 2]))
    ops = base.make_op_batch(
        op=[graph.OP_REMOVE_EDGE, graph.OP_ADD_VERTEX],
        key=[0, 0], a0=[1, 3], a1=[2, 0])
    _assert_replay_commutes(
        graph.SPEC, graph.init(1, 8, 8), origin, ops,
        perms=[(0, 1), (1, 0)])
    # gated ops: remove-vertex with a live incident edge was rejected at
    # capture time and stays rejected on every replica
    rv = base.make_op_batch(
        op=[graph.OP_REMOVE_VERTEX], key=[0], a0=[1])
    prepared = graph.SPEC.prepare_ops(origin, rv)
    assert prepared["ok"][0, 0] == 0
    st = graph.apply_ops(origin, prepared)
    assert bool(graph.contains_vertex(st, 0, 1))


def test_intra_batch_dependency_captured_sequentially():
    """A batch [add_vertex v, add_vertex w, add_edge v->w] submitted to
    SafeKV must yield the edge on every replica: each op's capture
    observes earlier ops of its own batch (capture_and_apply), matching
    the reference's per-object op serialization."""
    from janus_tpu.consensus import DagConfig
    from janus_tpu.runtime.safecrdt import SafeKV

    N, B = 4, 4
    kv = SafeKV(DagConfig(N, 8), graph.SPEC, ops_per_block=B,
                num_keys=2, v_capacity=8, e_capacity=8)
    op = np.zeros((N, B), np.int32)
    key = np.zeros((N, B), np.int32)
    a0 = np.zeros((N, B), np.int32)
    a1 = np.zeros((N, B), np.int32)
    op[0, :3] = [graph.OP_ADD_VERTEX, graph.OP_ADD_VERTEX, graph.OP_ADD_EDGE]
    a0[0, :3] = [1, 2, 1]
    a1[0, 2] = 2
    kv.submit(base.make_op_batch(op=op, key=key, a0=a0, a1=a1))
    # origin sees the edge instantly (fast path)
    assert bool(np.asarray(kv.query_prospective("edge_count"))[0, 0] == 1)
    for _ in range(4):
        kv.tick()
    counts = np.asarray(kv.query_stable("edge_count"))[:, 0]
    assert (counts == 1).all(), counts


def test_safekv_rejects_uncaptured_spec():
    import dataclasses

    import pytest

    from janus_tpu.consensus import DagConfig
    from janus_tpu.models import pncounter
    from janus_tpu.runtime.safecrdt import SafeKV

    bad = dataclasses.replace(
        pncounter.SPEC, name="Unsafe", type_code="_unsafe_test",
        replay_safe=False, prepare_ops=None)
    with pytest.raises(ValueError, match="not replay-safe"):
        SafeKV(DagConfig(4, 8), bad, ops_per_block=2,
               num_keys=2, num_writers=4)


def test_every_registered_type_is_replay_safe():
    """The registry-wide guarantee the runtime relies on."""
    for code, spec in base.registered_types().items():
        assert spec.replay_safe or spec.prepare_ops is not None, (
            f"type {code} is neither replay_safe nor effect-captured"
        )


def test_orset_batched_replay_matches_scan_path():
    """The batched captured-union replay (consensus delta apply) must be
    bit-equal to per-op scan application of the same captured ops —
    including at row capacity, where both paths keep the C smallest tags
    (a policy mismatch would silently diverge origin from replicas)."""
    import jax.numpy as jnp

    from janus_tpu.models import base, orset

    K, C = 3, 4
    st0 = orset.init(num_keys=K, capacity=C)
    # ops: fill key 0 past capacity, interleave removes
    raw = base.make_op_batch(
        op=np.asarray([1, 1, 1, 1, 2, 1, 1], np.int32),
        key=np.asarray([0, 0, 0, 0, 0, 0, 1], np.int32),
        a0=np.asarray([7, 7, 8, 8, 7, 9, 5], np.int32),
        a1=np.asarray([5, 6, 7, 8, 0, 1, 2], np.int32),
        a2=np.asarray([1, 1, 1, 1, 0, 1, 1], np.int32))
    # origin-style sequential capture produces the canonical op stream
    _, captured = base.capture_and_apply(orset.SPEC, st0, raw)

    # scan path: one op at a time
    st_scan = st0
    for i in range(7):
        one = {f: v[i][None] for f, v in captured.items()}
        st_scan = orset.apply_ops(st_scan, one)
    # batched path: whole stream at once
    st_batch = orset.apply_ops(st0, captured)
    for f in ("tag_rep", "tag_ctr", "elem", "removed", "valid"):
        np.testing.assert_array_equal(np.asarray(st_scan[f]),
                                      np.asarray(st_batch[f]), err_msg=f)
    # and grouping-insensitive: two halves applied separately
    st_half = orset.apply_ops(st0, {f: v[:4] for f, v in captured.items()})
    st_half = orset.apply_ops(st_half, {f: v[4:] for f, v in captured.items()})
    for f in ("tag_rep", "tag_ctr", "elem", "removed", "valid"):
        np.testing.assert_array_equal(np.asarray(st_half[f]),
                                      np.asarray(st_batch[f]), err_msg=f)


def test_orset_batched_capture_matches_sequential_scan():
    """prepare_ops_batch must be semantically exact vs the sequential
    per-op capture scan it replaces: identical POST-STATE always, and
    identical captured payloads while rows stay below capacity (at
    capacity the batched path may additionally capture a tag the scan
    saw evicted — documented, and dead-on-arrival in the union fold)."""
    import dataclasses

    import numpy as np

    from janus_tpu.models import base, orset

    seq_spec = dataclasses.replace(orset.SPEC, prepare_ops_batch=None,
                                   type_code="orset_seqtest")
    rng = np.random.default_rng(21)
    for trial in range(6):
        st_a = orset.init(num_keys=4, capacity=32, rm_capacity=8)
        st_b = orset.init(num_keys=4, capacity=32, rm_capacity=8)
        ctr = 0
        for _round in range(3):
            b = 24
            ops_np = {
                "op": rng.integers(orset.OP_ADD, orset.OP_CLEAR + 1, b),
                "key": rng.integers(0, 4, b),
                "a0": rng.integers(0, 5, b),
                "a1": rng.integers(0, 3, b),
                "a2": np.arange(ctr, ctr + b),
                "writer": np.zeros(b, np.int64),
            }
            ctr += b
            ops = base.make_op_batch(**{k: v.astype(np.int32)
                                        for k, v in ops_np.items()})
            st_a, prep_a = base.capture_and_apply(orset.SPEC, st_a, ops)
            st_b, prep_b = base.capture_and_apply(seq_spec, st_b, ops)
            for f in ("rm_rep", "rm_ctr", "rm_elem"):
                np.testing.assert_array_equal(
                    np.asarray(prep_a[f]), np.asarray(prep_b[f]),
                    err_msg=f"trial {trial} payload {f}")
            for f in st_a:
                if f == "_rm_cap":
                    continue
                np.testing.assert_array_equal(
                    np.asarray(st_a[f]), np.asarray(st_b[f]),
                    err_msg=f"trial {trial} state {f}")
