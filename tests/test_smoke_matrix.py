"""Tier-1 smoke of the full benchmark matrix with telemetry live.

Runs every harness preset once at the shrunken --smoke geometry
(scripts/run_bench_matrix.py) and holds the telemetry plane to its
budget: the metrics fast path must cost < 2% of each run's wall clock.
This is the regression net for "someone added an instrument inside the
tick loop that isn't tick-loop cheap".
"""
import importlib.util
import json
import pathlib


def _load_matrix_module():
    path = (pathlib.Path(__file__).resolve().parent.parent
            / "scripts" / "run_bench_matrix.py")
    spec = importlib.util.spec_from_file_location("run_bench_matrix", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_smoke_matrix_all_presets(tmp_path):
    from janus_tpu.bench.harness import PRESETS

    mod = _load_matrix_module()
    out = tmp_path / "smoke.jsonl"
    # raises AssertionError itself if any preset blows the 2% budget
    mod.run_smoke(str(out))

    rows = [json.loads(line) for line in out.read_text().splitlines()]
    # + the flight-overhead row + the SLO-plane row + the anatomy row
    # + the overload-control row
    assert len(rows) == len(PRESETS) + 4
    by_run = {r["run"]: r for r in rows}
    for name in PRESETS:
        if name == "overload":
            continue   # dedicated row + assertions below
        row = by_run[f"smoke_{name}"]
        smoke = row["smoke"]
        # telemetry was actually live (rga replays through jit_tick
        # directly, not SafeKV, so it records no stage histograms) —
        # and actually cheap
        if name != "rga":
            assert smoke["hist_records"] > 0, name
        assert smoke["overhead_pct"] < 2.0, name
    # the adaptive presets must report their controller evidence,
    # including the per-stage mean/p90 the PR-3 satellite threaded in
    adaptive = by_run["smoke_orset_adaptive"]
    assert adaptive["block_ceiling"] >= adaptive["block_floor"]
    assert "stages" in adaptive and "commit" in adaptive["stages"]
    assert "mean_ms" in adaptive["stages"]["commit"]
    assert "p90_ms" in adaptive["stages"]["commit"]
    # the clean delta run must come out healthy
    assert by_run["smoke_mixed_delta"]["health"]["status"] == "OK"
    # sharded service plane: the A/B arms replayed the same schedule and
    # read back bit-equal final state (the run itself asserts equality
    # against the schedule's predicted sums before emitting the row)
    ws = by_run["smoke_wire_sharded"]
    assert ws["states_bitequal"] is True
    assert ws["arm_sharded"]["shards"] >= 2
    assert ws["arm_unsharded"]["goodput_ops_per_sec"] > 0
    assert ws["arm_sharded"]["goodput_ops_per_sec"] > 0
    # native demux A/B: the native ring and the Python router replayed
    # the same schedule at equal shard count and read back bit-equal
    # state; the native arm's ledger reconciled exactly (run_smoke
    # gates these too — re-assert the row shape for jsonl consumers)
    wn = by_run["smoke_wire_sharded_native"]
    assert wn["states_bitequal"] is True
    assert wn["arm_pyrouter"]["shards"] >= 2
    assert wn["arm_native"]["shards"] == wn["arm_pyrouter"]["shards"]
    assert wn["arm_pyrouter"]["native_demux"] is False
    assert wn["arm_native"]["native_demux"] is True
    assert wn["arm_pyrouter"]["goodput_ops_per_sec"] > 0
    assert wn["arm_native"]["goodput_ops_per_sec"] > 0
    assert wn["demux_speedup"] > 0
    assert abs(wn["slo_report"]["replied_vs_total"] - 1.0) <= 0.01
    # flight recorder: tracing was live (events flowed) and cheap
    fl = by_run["smoke_flight_overhead"]["smoke"]
    assert fl["flight_events"] > 0
    assert fl["overhead_pct"] < 3.0
    # SLO plane (run_smoke already gates these; re-assert the row shape
    # so the jsonl consumers — fold_slo_reports, dashboards — can rely
    # on it): out-of-band scrapes ran concurrently with the loaded
    # sharded arm, stayed sub-250ms, and the ledger reconciled
    sp = by_run["smoke_slo_plane"]
    sr, oob = sp["slo_report"], sp["oob"]
    assert sp["smoke"]["e2e_samples"] > 0
    assert sp["smoke"]["ledger_overhead_pct"] < 2.0
    assert sr["unsafe"]["e2e_p99_ms"] >= sr["unsafe"]["e2e_p50_ms"] > 0
    assert abs(sr["replied_vs_total"] - 1.0) <= 0.01
    assert oob["scrapes"] > 0 and oob["scrape_errors"] == 0
    assert oob["health_ms"] < 250.0 and oob["slo_ms"] < 250.0
    assert oob["cpu_frac"] < 0.02
    # latency anatomy (run_smoke gates these; re-assert the row shape):
    # the native sharded arm's segment histograms decomposed its e2e
    # latency per op class — the gate accepts >= 95% p50 coverage OR
    # the exact ns-sum identity within +-5% (medians don't sum across
    # skewed correlated segments) — the reply ledger reconciled
    # EXACTLY, and the 2-process probe merged both hosts' spans onto
    # one clock-aligned timeline with every router->shard handoff
    # lane ordered
    an = by_run["smoke_anatomy"]
    assert an["smoke"]["classes"], "no op class recorded segments"
    for cls, cov in an["smoke"]["coverage_p50"].items():
        cov_ns = an["smoke"]["coverage_ns"][cls]
        # no floor on cov alone: under a degraded bimodal run (ring
        # p50 0.2s / mean 1.3s observed under full-suite pressure)
        # sum-of-medians legitimately collapses while the ns identity
        # still reconciles to ~1.000 — that identity is the invariant
        assert cov >= 0.95 or abs(cov_ns - 1.0) <= 0.05, \
            (cls, cov, cov_ns)
    assert an["smoke"]["replied_vs_total"] == 1.0
    assert an["smoke"]["seg_overhead_pct"] < 2.0
    mt = an["smoke"]["merged_trace"]
    assert mt["nodes"] == ["h0", "h1"]
    assert all(n > 0 for n in mt["spans_per_node"].values())
    assert mt["handoff_lanes"] > 0
    assert mt["handoff_ordered"] == mt["handoff_lanes"]
    assert set(mt["clock"]) == {"h0", "h1"}
    for peer in mt["clock"].values():
        assert peer["rtt_ns"] > 0
    # overload control (run_smoke gates these; re-assert the row shape):
    # the offered-load sweep engaged admission control at 1x and at a
    # deep point far past true capacity, goodput held (plateau, not
    # collapse) past saturation, every point reconciled
    # offered == admitted + shed exactly, safe/stable ops were never
    # shed, the pipeline never stalled, and the controller's own cost
    # stayed under the telemetry budget
    ovl = by_run["smoke_overload"]
    ov = ovl["overload_report"]
    sweep = {p["mult"]: p for p in ov["sweep"]}
    deep = max(sweep)
    assert set(sweep) == {1.0, deep} and deep > 1.0
    assert ovl["smoke"]["deep_mult"] == deep
    assert ovl["smoke"]["goodput_ratio"] >= 0.9
    assert ovl["smoke"]["points_reconciled"] == len(sweep)
    for p in ov["sweep"]:
        assert p["offered"] == p["admitted"] + p["shed"]
        assert p["commit_stalls"] == 0
    # the deep point actually overloaded the door: something was shed
    # and the nacks reached live clients (the drain threads may trail
    # the server ledger by a scan, so bound rather than demand equality)
    assert sweep[deep]["shed"] > 0
    assert 0 < sweep[deep]["client_shed_replies"] <= sweep[deep]["shed"]
    assert ov["safe_shed_total"] == 0
    assert ov["stable_shed_total"] == 0
    assert ov["goodput_plateau_frac"] >= 0.0
    assert ovl["smoke"]["controller_overhead_frac_max"] < 0.02
