"""Integrity-plane tests: real digests/signatures over emulated blocks,
honest refusal to ack invalid blocks, Byzantine invalid-signature
injection, and pruning.

Reference analog: Block digest/sign/verify round trips
(Tests/DAGBlockAndMsgTests.cs), FaultyDAGTests — a node emitting invalid
certificates at 50%% keeps the cluster live and its bad blocks get
pruned (Tests/DAGTests.cs:1308-1453, PruneInvalidBlocks DAG.cs:258-297).
"""
import numpy as np

from janus_tpu.consensus import DagConfig
from janus_tpu.consensus.integrity import (
    IntegrityPlane,
    SecureCluster,
    generate_committee,
)
from janus_tpu.models import base, pncounter
from janus_tpu.runtime.safecrdt import SafeKV

N, W, B, K = 4, 16, 4, 8


def pnc_ops(rng):
    shape = (N, B)
    return base.make_op_batch(
        op=rng.integers(pncounter.OP_INC, pncounter.OP_DEC + 1, shape),
        key=rng.integers(0, K, shape),
        a0=rng.integers(1, 5, shape),
        writer=np.broadcast_to(np.arange(N, dtype=np.int32)[:, None], shape))


def make_secure(**plane_kw):
    cfg = DagConfig(N, W)
    kv = SafeKV(cfg, pncounter.SPEC, ops_per_block=B,
                num_keys=K, num_writers=N)
    plane = IntegrityPlane(cfg, **plane_kw)
    return SecureCluster(kv, plane)


def test_block_digest_covers_content():
    cfg = DagConfig(N, W)
    plane = IntegrityPlane(cfg)
    prev = np.asarray([True, True, True, False])
    d1 = plane.block_digest(5, 1, prev, b"payload")
    assert d1 != plane.block_digest(5, 2, prev, b"payload")   # source
    assert d1 != plane.block_digest(6, 1, prev, b"payload")   # round
    assert d1 != plane.block_digest(5, 1, prev, b"other")     # payload
    prev2 = np.asarray([True, False, True, False])
    assert d1 != plane.block_digest(5, 1, prev2, b"payload")  # edges


def test_honest_run_all_blocks_verify():
    sc = make_secure()
    rng = np.random.default_rng(0)
    for _ in range(2 * W):
        sc.step(pnc_ops(rng), safe=np.ones((N, B), bool))
    assert sc.plane.verified_bad == 0
    assert sc.plane.verified_ok >= 2 * W * N - N  # every created block
    assert sc.plane.pruned_blocks() == []
    idle = base.make_op_batch(op=np.zeros((N, B), np.int32))
    for _ in range(8):  # drain in-flight blocks
        sc.step(idle, record=False)
    stable = np.asarray(sc.kv.query_stable("get"))
    prosp = np.asarray(sc.kv.query_prospective("get"))
    assert (stable == stable[0]).all()
    np.testing.assert_array_equal(stable, prosp)


def test_byzantine_invalid_signatures_pruned_liveness_kept():
    """Node 3 signs tampered digests half the time: the cluster stays
    live, every pruned block is node 3's, the prune count tracks the
    faulty rate, and honest nodes converge identically — node 3's
    invalid blocks contribute nothing to any honest state."""
    sc = make_secure(byzantine=np.asarray([False, False, False, True]),
                     invalid_rate=0.5, seed=7)
    rng = np.random.default_rng(1)
    ticks = 4 * W
    for _ in range(ticks):
        sc.step(pnc_ops(rng))
    # liveness: rounds and the GC frontier keep advancing
    assert int(np.asarray(sc.kv.dag["node_round"]).min()) > ticks // 2
    assert sc.kv.base_round() > W

    pruned = sc.plane.pruned_blocks()
    assert pruned, "no invalid blocks detected"
    assert all(src == 3 for _, src in pruned)
    # ~half of node 3's blocks invalid (binomial; generous bounds)
    frac = len(pruned) / ticks
    assert 0.25 < frac < 0.75, frac

    # drain and check honest convergence
    idle = base.make_op_batch(op=np.zeros((N, B), np.int32))
    for _ in range(2 * W):
        sc.step(idle, record=False)
    stable = np.asarray(sc.kv.query_stable("get"))
    prosp = np.asarray(sc.kv.query_prospective("get"))
    honest = [0, 1, 2]
    for v in honest[1:]:
        np.testing.assert_array_equal(stable[0], stable[v])
        np.testing.assert_array_equal(prosp[0], prosp[v])
    np.testing.assert_array_equal(stable[honest][0], prosp[honest][0])


def test_committee_key_table():
    com = generate_committee(4, seed=3)
    assert len(com) == 4
    assert set(com.keys) == {0, 1, 2, 3}
    # distinct identities
    assert len({r.pub for r in com.replicas}) == 4


def test_no_fetch_mirror_matches_fetch_mode():
    """The host-side lockstep mirror (zero extra device fetches) must
    drive the plane to EXACTLY the same digests/signing/pruning as the
    fetch-mode path — same Byzantine injection, same commit outcomes
    (VERDICT round-3 item 6)."""
    rng_a = np.random.default_rng(11)
    rng_b = np.random.default_rng(11)
    byz = np.asarray([False, False, False, True])

    def build(no_fetch):
        cfg = DagConfig(N, W)
        kv = SafeKV(cfg, pncounter.SPEC, ops_per_block=B,
                    num_keys=K, num_writers=N)
        plane = IntegrityPlane(cfg, byzantine=byz, invalid_rate=0.5, seed=3)
        return SecureCluster(kv, plane, no_fetch=no_fetch)

    fast, slow = build(True), build(False)
    for _ in range(4 * W):
        fast.step(pnc_ops(rng_a))
        slow.step(pnc_ops(rng_b))
    assert fast.plane.pruned_blocks() == slow.plane.pruned_blocks()
    assert fast.plane.verified_bad == slow.plane.verified_bad > 0
    for f in fast.kv.dag:
        np.testing.assert_array_equal(
            np.asarray(fast.kv.dag[f]), np.asarray(slow.kv.dag[f]),
            err_msg=f)
    stable_f = np.asarray(fast.kv.query_stable("get"))
    stable_s = np.asarray(slow.kv.query_stable("get"))
    np.testing.assert_array_equal(stable_f, stable_s)
    assert fast.kv.ordered_commits(0) == slow.kv.ordered_commits(0)
