"""Lattice-algebra kernel tests (mirrors the pure-semantics layer of
reference MergeSharp.Tests — no I/O, just join laws)."""
import jax.numpy as jnp
import numpy as np

from janus_tpu.ops import (
    clock_compare,
    clock_dominates,
    clock_leq,
    join_max,
    join_or,
    ts_after,
    ts_max,
)
from janus_tpu.ops.lattice import (
    CLOCK_AFTER,
    CLOCK_BEFORE,
    CLOCK_CONCURRENT,
    CLOCK_EQUAL,
)


def test_join_max_laws(rng):
    a, b, c = (jnp.asarray(rng.integers(0, 100, (4, 7, 3)), jnp.int32) for _ in range(3))
    # commutative, associative, idempotent
    np.testing.assert_array_equal(join_max(a, b), join_max(b, a))
    np.testing.assert_array_equal(
        join_max(a, join_max(b, c)), join_max(join_max(a, b), c)
    )
    np.testing.assert_array_equal(join_max(a, a), a)


def test_join_or_laws(rng):
    a, b = (jnp.asarray(rng.integers(0, 2, (5, 9)), bool) for _ in range(2))
    np.testing.assert_array_equal(join_or(a, b), join_or(b, a))
    np.testing.assert_array_equal(join_or(a, a), a)


def test_clock_compare_codes():
    a = jnp.array([[1, 2, 3]], jnp.int32)
    assert clock_compare(a, a)[0] == CLOCK_EQUAL
    assert clock_compare(a, a + 1)[0] == CLOCK_BEFORE
    assert clock_compare(a + 1, a)[0] == CLOCK_AFTER
    b = jnp.array([[2, 1, 3]], jnp.int32)
    assert clock_compare(a, b)[0] == CLOCK_CONCURRENT
    assert not clock_dominates(a, a)[0]
    assert clock_dominates(a + 1, a)[0]
    assert clock_leq(a, a)[0]


def test_clock_compare_batched(rng):
    a = jnp.asarray(rng.integers(0, 4, (64, 8)), jnp.int32)
    b = jnp.asarray(rng.integers(0, 4, (64, 8)), jnp.int32)
    codes = np.asarray(clock_compare(a, b))
    an, bn = np.asarray(a), np.asarray(b)
    for i in range(64):
        ale, ble = (an[i] <= bn[i]).all(), (bn[i] <= an[i]).all()
        want = (
            CLOCK_EQUAL if ale and ble else CLOCK_BEFORE if ale
            else CLOCK_AFTER if ble else CLOCK_CONCURRENT
        )
        assert codes[i] == want


def test_ts_pair_order_unsigned_low_word():
    """Low words with bit 31 set must order as unsigned (regression)."""
    a_hi, a_lo = jnp.int32(0), jnp.int32(-(2**31))  # counter 0x80000000
    b_hi, b_lo = jnp.int32(0), jnp.int32(2**31 - 1)  # counter 0x7FFFFFFF
    assert bool(ts_after(a_hi, a_lo, b_hi, b_lo))
    assert not bool(ts_after(b_hi, b_lo, a_hi, a_lo))
    mh, ml = ts_max(b_hi, b_lo, a_hi, a_lo)
    assert (int(mh), int(ml)) == (0, -(2**31))


def test_ts_pair_order(rng):
    hi_a, lo_a, hi_b, lo_b = (
        jnp.asarray(rng.integers(0, 3, (128,)), jnp.int32) for _ in range(4)
    )
    after = np.asarray(ts_after(hi_a, lo_a, hi_b, lo_b))
    va = np.asarray(hi_a).astype(np.int64) * (1 << 32) + np.asarray(lo_a)
    vb = np.asarray(hi_b).astype(np.int64) * (1 << 32) + np.asarray(lo_b)
    np.testing.assert_array_equal(after, va >= vb)
    mh, ml = ts_max(hi_a, lo_a, hi_b, lo_b)
    vm = np.asarray(mh).astype(np.int64) * (1 << 32) + np.asarray(ml)
    np.testing.assert_array_equal(vm, np.maximum(va, vb))
