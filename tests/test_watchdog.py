"""Health-watchdog tests: commit stall, recompile storm, overflow
streak, equivocation — each edge-triggered, with exactly one flight-
recorder evidence dump per activation.

The stall test drives the REAL service: ops staged, ``_step_type``
suppressed, so the pipeline genuinely makes no commit progress while
work is pending — the exact wedge the watchdog exists to catch.
"""
import json
import time

import numpy as np

from janus_tpu.obs import flight
from janus_tpu.obs.flight import FlightRecorder
from janus_tpu.obs.metrics import Registry
from janus_tpu.obs.watchdog import (
    DEGRADED,
    OK,
    STALLED,
    HealthWatchdog,
    WatchdogConfig,
)


def _wd(tmp_path=None, **kw):
    rec = FlightRecorder(capacity=64)
    rec.event("c1", "seal", "S", detail=10)  # something to dump
    cfg = WatchdogConfig(dump_dir=str(tmp_path) if tmp_path else None, **kw)
    return HealthWatchdog(cfg, registry=Registry(), recorder=rec)


def test_health_ok_when_quiet():
    wd = _wd()
    h = wd.health()
    assert h["status"] == OK
    assert h["reasons"] == []
    assert h["dumps"] == 0


def test_commit_stall_detects_clears_and_dumps_once_per_activation(tmp_path):
    wd = _wd(tmp_path, stall_ticks=3)
    for _ in range(10):
        wd.observe_commits("pnc", own_commits=7, pending_ops=12)
    h = wd.health()
    assert h["status"] == STALLED
    assert any("no commit" in r for r in h["reasons"])
    # edge-triggered: 10 stalled observations, ONE evidence dump
    assert len(list(tmp_path.glob("flight_commit_stall_*.jsonl"))) == 1
    # progress clears the anomaly and re-arms the detector
    wd.observe_commits("pnc", own_commits=8, pending_ops=12)
    assert wd.health()["status"] == OK
    for _ in range(10):
        wd.observe_commits("pnc", own_commits=8, pending_ops=12)
    assert wd.health()["status"] == STALLED
    assert len(list(tmp_path.glob("flight_commit_stall_*.jsonl"))) == 2


def test_drained_queue_is_not_a_stall():
    wd = _wd(stall_ticks=2)
    for _ in range(10):
        wd.observe_commits("pnc", own_commits=5, pending_ops=0)
    assert wd.health()["status"] == OK


def test_no_dump_when_recorder_disabled(tmp_path):
    rec = FlightRecorder(capacity=8, enabled=False)
    wd = HealthWatchdog(
        WatchdogConfig(stall_ticks=1, dump_dir=str(tmp_path)),
        registry=Registry(), recorder=rec)
    for _ in range(5):
        wd.observe_commits("x", 1, 1)
    assert wd.health()["status"] == STALLED
    assert list(tmp_path.iterdir()) == []  # nothing worth capturing


def test_recompile_storm_fires_on_shape_churn():
    """Real retraces: churning the fused megatick's batch shape forces
    an XLA trace per tick, which the storm detector must flag."""
    from janus_tpu.models import base, pncounter
    from janus_tpu.runtime.store import Store

    wd = _wd(recompile_window=8, recompile_limit=3)
    store = Store(2, {"pnc": dict(num_keys=8, num_writers=2)})
    rng = np.random.default_rng(0)
    for t in range(5):
        B = 2 + t  # new batch shape every tick -> retrace every tick
        ops = {"pnc": base.make_op_batch(
            op=np.full((2, B), pncounter.OP_INC, np.int32),
            key=rng.integers(0, 8, (2, B)).astype(np.int32),
            a0=np.ones((2, B), np.int32),
            writer=np.broadcast_to(
                np.arange(2, dtype=np.int32)[:, None], (2, B)).copy())}
        store.fused_tick(ops, delta=False)
        wd.observe_trace_count("store", store.fused_trace_count)
    h = wd.health()
    assert h["status"] == DEGRADED
    assert any("retraces" in r for r in h["reasons"])


def test_stable_shapes_no_storm():
    wd = _wd(recompile_window=8, recompile_limit=3)
    for _ in range(20):
        wd.observe_trace_count("store", 1)  # compiled once, reused
    assert wd.health()["status"] == OK


def test_overflow_streak_degrades_then_clears():
    wd = _wd(overflow_streak=4)
    total = 0
    for _ in range(6):
        total += 1  # overflowing every tick
        wd.observe_overflow("orset", total)
    h = wd.health()
    assert h["status"] == DEGRADED
    assert any("overflowed" in r for r in h["reasons"])
    wd.observe_overflow("orset", total)  # flat: budget held this tick
    assert wd.health()["status"] == OK


def test_equivocation_flags_worst_node():
    wd = _wd(equivocation_limit=0)
    wd.observe_equivocation({3: 0, 7: 5})
    h = wd.health()
    assert h["status"] == DEGRADED
    assert any("node 7" in r for r in h["reasons"])
    assert h["equivocation"] == {3: 0, 7: 5}
    wd.observe_equivocation({3: 0, 7: 0})
    assert wd.health()["status"] == OK


def test_shed_storm_counts_loaded_ticks_only(tmp_path):
    """The storm detector feeds on cumulative shed/offered counters:
    heavy-shed loaded ticks extend the streak, idle ticks (no offered
    delta) neither extend nor reset it, and one clean loaded tick
    clears — edge-triggered, one dump per activation."""
    wd = _wd(tmp_path, shed_storm_ticks=3, shed_storm_frac=0.5)
    shed, offered = 0, 0
    wd.observe_shed("s0", shed, offered)  # baseline only, no verdict
    assert wd.health()["status"] == OK
    # two heavy ticks (60/100 >= 0.5): streak at 2, still below ticks
    for _ in range(2):
        shed += 60
        offered += 100
        wd.observe_shed("s0", shed, offered)
    assert wd.health()["status"] == OK
    # an idle tick in between must NOT reset the streak
    wd.observe_shed("s0", shed, offered)
    assert wd.health()["status"] == OK
    # third heavy tick trips the storm
    shed += 60
    offered += 100
    wd.observe_shed("s0", shed, offered)
    h = wd.health()
    assert h["status"] == DEGRADED
    assert any("shed_storm:s0" in r for r in h["reasons"])
    assert len(list(tmp_path.glob("flight_shed_storm_*.jsonl"))) == 1
    # more heavy ticks: still one dump (edge-triggered)
    shed += 60
    offered += 100
    wd.observe_shed("s0", shed, offered)
    assert len(list(tmp_path.glob("flight_shed_storm_*.jsonl"))) == 1
    # a loaded tick below the fraction clears and re-arms
    offered += 100
    wd.observe_shed("s0", shed, offered)
    assert wd.health()["status"] == OK
    for _ in range(3):
        shed += 60
        offered += 100
        wd.observe_shed("s0", shed, offered)
    assert wd.health()["status"] == DEGRADED
    assert len(list(tmp_path.glob("flight_shed_storm_*.jsonl"))) == 2


def test_shed_below_fraction_never_storms():
    wd = _wd(shed_storm_ticks=2, shed_storm_frac=0.5)
    shed, offered = 0, 0
    wd.observe_shed("s0", shed, offered)
    for _ in range(10):
        shed += 10       # 10% per tick: working as intended
        offered += 100
        wd.observe_shed("s0", shed, offered)
    assert wd.health()["status"] == OK


def test_key_exchange_verdict_sets_and_clears():
    wd = _wd()
    wd.observe_key_exchange("pnc", "key exchange incomplete after 512 "
                                   "steps (missing nodes [3])")
    h = wd.health()
    assert h["status"] == DEGRADED
    assert any("key_exchange:pnc" in r and "missing nodes" in r
               for r in h["reasons"])
    wd.observe_key_exchange("pnc", None)  # exchange completed
    assert wd.health()["status"] == OK


def test_service_shed_storm_end_to_end():
    """Sustained overload through the real sharded service: flood one
    shard's door past its hard cap tick after tick until the worker's
    shed-storm detector pages DEGRADED through the in-band `health`
    answer, then let admitted-only traffic clear it."""
    import numpy as np

    from janus_tpu.net import JanusClient, JanusConfig, JanusService, TypeConfig
    from janus_tpu.obs.watchdog import HealthWatchdog as _HW
    from janus_tpu.obs.watchdog import WatchdogConfig as _WC

    svc = JanusService(JanusConfig(
        num_nodes=4, window=8, ops_per_block=8, shards=2,
        native_demux=False, inbox_hard_cap=8,
        types=(TypeConfig("pnc", {"num_keys": 16}),)))
    port = svc.start(pump=False)

    def pump(n=8):
        for _ in range(n):
            svc.step()
            for w in svc.workers:
                w.step()
            time.sleep(0.005)

    try:
        with JanusClient("127.0.0.1", port) as c:
            seq = c.send("pnc", "acct", "s")
            pump(8)
            assert c.wait(seq, timeout=30)["result"] == "success"
            pump(40)  # commit the create before the flood
            # shorter streak so the e2e stays seconds-cheap; same
            # registry/recorder wiring the service gave its workers
            for w in svc.workers:
                w.watchdog = _HW(_WC(shed_storm_ticks=4, stall_ticks=200),
                                 registry=Registry())
            # each flood round: route 32 ops at a door with room 8 ->
            # a >= 50% shed tick on the owning worker's next step
            for _ in range(8):
                c.send_batch("pnc", ["acct"], np.zeros(32, np.int32),
                             "i", p0=np.ones(32, np.int64))
                time.sleep(0.01)  # let the frame reach the router poll
                pump(1)
            deg = json.loads(str(
                _rt(c, svc, "health", "_", "g")["result"]))
            assert deg["status"] == DEGRADED
            assert any("shed_storm" in r for r in deg["reasons"])
            # recovery: admitted-only traffic (below the cap) gives the
            # worker clean loaded ticks, which clear the storm
            for _ in range(6):
                c.send_batch("pnc", ["acct"], np.zeros(4, np.int32),
                             "i", p0=np.ones(4, np.int64))
                pump(2)
            ok = json.loads(str(
                _rt(c, svc, "health", "_", "g")["result"]))
            assert ok["status"] == OK
    finally:
        svc.stop()


def _rt(c, svc, *send_args, **send_kw):
    """Manual-pump roundtrip against a pump=False sharded service."""
    seq = c.send(*send_args, **send_kw)
    for _ in range(8):
        svc.step()
        for w in svc.workers:
            w.step()
        time.sleep(0.01)
    return c.wait(seq, timeout=30)


def test_service_commit_stall_end_to_end(tmp_path):
    """Synthetic wedge through the real service: stage safe ops, then
    suppress the per-type step so no block ever seals or commits. The
    watchdog must flip the in-band `health` answer to STALLED and dump
    the flight recorder exactly once; un-wedging recovers to OK."""
    from janus_tpu.net import JanusClient, JanusConfig, JanusService, TypeConfig

    rec = flight.enable()
    rec.clear()
    svc = JanusService(JanusConfig(
        num_nodes=4, window=8, ops_per_block=8,
        watchdog_stall_ticks=3, flight_dump_dir=str(tmp_path),
        types=(TypeConfig("pnc", {"num_keys": 16}),)))
    port = svc.start(pump=False)

    def roundtrip(c, *send_args, **send_kw):
        # no pump thread: step the service by hand between send and wait
        seq = c.send(*send_args, **send_kw)
        for _ in range(8):
            svc.step()
            time.sleep(0.01)  # let the reply frame reach the client
        return c.wait(seq, timeout=30)

    try:
        with JanusClient("127.0.0.1", port) as c:
            assert roundtrip(c, "pnc", "acct", "s")["result"] == "success"
            # run the create through consensus: ops on a key whose
            # create has not committed wait OFF the pending queues (the
            # stall detector's evidence), so materialize it first
            for _ in range(40):
                svc.step()

            orig = svc._step_type
            svc._step_type = lambda rt: False  # wedge the pipeline
            c.send("pnc", "acct", "i", ["1"], is_safe=True)
            # step until the op's frame lands and the stall detector
            # arms (frame arrival is asynchronous wrt step())
            for _ in range(100):
                svc.step()
                time.sleep(0.01)
                if svc.watchdog.health()["status"] == STALLED:
                    break
            h = json.loads(str(roundtrip(c, "health", "_", "g")["result"]))
            assert h["status"] == STALLED
            assert any("commit_stall" in r for r in h["reasons"])
            dumps = list(tmp_path.glob("flight_commit_stall_*.jsonl"))
            assert len(dumps) == 1  # one activation, one dump
            assert dumps[0].stat().st_size > 0
            # the in-band `trace` command serves the same evidence as
            # Perfetto-loadable JSON while the recorder is live
            doc = json.loads(str(roundtrip(c, "trace", "_", "g")["result"]))
            assert any(e.get("ph") == "X" and e["name"] == "ingest"
                       for e in doc["traceEvents"])

            svc._step_type = orig  # un-wedge; commits resume
            for _ in range(60):
                svc.step()
                if svc.watchdog.health()["status"] == OK:
                    break
            assert svc.watchdog.health()["status"] == OK
            # the wedge produced no second dump after recovery
            assert len(list(
                tmp_path.glob("flight_commit_stall_*.jsonl"))) == 1
    finally:
        flight.disable()
        svc.stop()
