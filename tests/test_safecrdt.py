"""SafeCRDT dual-state runtime tests — the analog of the reference's
full-system suite (Tests/KVStoreTests.cs: 4 complete server stacks in one
process; prospective convergence :141-159, stable==prospective
convergence :225-286, safe-update blocking semantics :289-354)."""
import jax.numpy as jnp
import numpy as np

from janus_tpu.consensus import DagConfig
from janus_tpu.models import base, orset, pncounter
from janus_tpu.runtime.safecrdt import SafeKV
from janus_tpu.utils.ids import TagMinter

N, W, B, K = 4, 16, 4, 8


def make_kv(**kw):
    return SafeKV(DagConfig(N, W), pncounter.SPEC, ops_per_block=B,
                  num_keys=K, num_writers=N, **kw)


def pnc_ops(key_amounts):
    """key_amounts: per node, list of (key, amount) — pads to B."""
    op = np.zeros((N, B), np.int32)
    key = np.zeros((N, B), np.int32)
    a0 = np.zeros((N, B), np.int32)
    writer = np.broadcast_to(np.arange(N, dtype=np.int32)[:, None], (N, B)).copy()
    for v, pairs in enumerate(key_amounts):
        for b, (k, a) in enumerate(pairs):
            op[v, b] = pncounter.OP_INC if a >= 0 else pncounter.OP_DEC
            key[v, b] = k
            a0[v, b] = abs(a)
    return base.make_op_batch(op=op, key=key, a0=a0, writer=writer)


def test_local_update_is_immediately_prospective():
    kv = make_kv()
    acc = kv.submit(pnc_ops([[(0, 5)], [], [], []]))
    assert acc.all()
    vals = np.asarray(kv.query_prospective("get"))  # [N, K]
    assert vals[0, 0] == 5          # origin sees it instantly
    assert (vals[1:, 0] == 0).all()  # others haven't yet
    assert (np.asarray(kv.query_stable("get")) == 0).all()


def test_prospective_converges_after_certification():
    kv = make_kv()
    kv.submit(pnc_ops([[(0, 5)], [(1, 3)], [], []]))
    kv.tick()  # round 0: blocks created, certified, delivered
    vals = np.asarray(kv.query_prospective("get"))
    assert (vals[:, 0] == 5).all() and (vals[:, 1] == 3).all()


def test_stable_lags_then_matches_prospective():
    kv = make_kv()
    kv.submit(pnc_ops([[(0, 5)], [(1, 3)], [(2, -2)], []]))
    committed_any = False
    for _ in range(4):
        new_com = kv.tick()
        committed_any = committed_any or new_com.any()
    assert committed_any
    stable = np.asarray(kv.query_stable("get"))
    prosp = np.asarray(kv.query_prospective("get"))
    np.testing.assert_array_equal(stable, prosp)
    assert stable[0, 0] == 5 and stable[0, 2] == -2
    # all nodes' stable states identical
    assert (stable == stable[0]).all()


def test_safe_update_completion_signal_and_latency():
    kv = make_kv()
    safe = np.zeros((N, B), bool)
    safe[0, 0] = True
    acc = kv.submit(pnc_ops([[(3, 7)], [], [], []]), safe=safe)
    assert acc[0]
    waited = None
    for i in range(6):
        new_com = kv.tick()
        # node 0's own block (round 0, source 0) committed in its own view?
        if new_com[0, 0, 0]:
            waited = i + 1
            break
    assert waited is not None, "safe update never committed"
    lats = kv.commit_latencies()
    assert len(lats) >= 1 and (lats >= 1).all()
    # the safe op's effect is in stable state everywhere
    assert (np.asarray(kv.query_stable("get"))[:, 3] == 7).all()


def test_continuous_load_converges_and_orders_identically():
    kv = make_kv()
    rng = np.random.default_rng(1)
    for t in range(10):
        pairs = [[(int(rng.integers(0, K)), int(rng.integers(-4, 5)))]
                 for _ in range(N)]
        kv.submit(pnc_ops(pairs))
        kv.tick()
    for _ in range(3):
        kv.tick()  # drain
    stable = np.asarray(kv.query_stable("get"))
    assert (stable == stable[0]).all()
    orders = [kv.ordered_commits(v) for v in range(N)]
    shortest = min(len(o) for o in orders)
    assert shortest > 0
    for o in orders:
        assert o[:shortest] == orders[0][:shortest]


def test_stalled_node_submit_rejected():
    kv = make_kv()
    # stall: only nodes 0,1 active -> no quorum -> no cert -> no advance
    act = jnp.asarray([True, True, False, False])
    kv.submit(pnc_ops([[(0, 1)], [(0, 1)], [], []]))
    kv.tick(active=act)
    # blocks for round 0 now exist but the cluster cannot advance;
    # resubmitting targets the same sealed slot -> rejected
    acc = kv.submit(pnc_ops([[(0, 9)], [], [], []]))
    assert not acc[0]
    vals = np.asarray(kv.query_prospective("get"))
    assert vals[0, 0] == 1  # rejected ops did not apply locally either


def test_orset_safekv_add_remove_consensus():
    kv = SafeKV(DagConfig(N, W), orset.SPEC, ops_per_block=B,
                num_keys=4, capacity=16)
    minters = [TagMinter(v) for v in range(N)]
    op = np.zeros((N, B), np.int32)
    key = np.zeros((N, B), np.int32)
    a0 = np.zeros((N, B), np.int32)
    a1 = np.zeros((N, B), np.int32)
    a2 = np.zeros((N, B), np.int32)
    for v in range(N):
        t = minters[v].mint_many(1)[0]
        op[v, 0] = orset.OP_ADD
        key[v, 0] = 1
        a0[v, 0] = 42
        a1[v, 0], a2[v, 0] = t
    kv.submit(base.make_op_batch(op=op, key=key, a0=a0, a1=a1, a2=a2))
    for _ in range(4):
        kv.tick()
    assert np.asarray(kv.query_prospective("contains", 1, 42)).all()
    assert np.asarray(kv.query_stable("contains", 1, 42)).all()
    # remove everywhere via one node, then converge
    op2 = np.zeros((N, B), np.int32)
    key2 = np.zeros((N, B), np.int32)
    a02 = np.zeros((N, B), np.int32)
    op2[0, 0] = orset.OP_REMOVE
    key2[0, 0] = 1
    a02[0, 0] = 42
    kv.submit(base.make_op_batch(op=op2, key=key2, a0=a02))
    for _ in range(4):
        kv.tick()
    assert not np.asarray(kv.query_stable("contains", 1, 42)).any()


def test_keyspace_assignment_and_capacity():
    from janus_tpu.runtime.keyspace import KeySpace
    ks = KeySpace({"pnc": 2, "orset": 4})
    assert ks.create("pnc", "alice") == 0
    assert ks.create("pnc", "bob") == 1
    assert ks.create("pnc", "alice") == 0  # idempotent
    assert ks.lookup("pnc", "carol") is None
    slot, existed = ks.resolve("pnc", "bob")
    assert slot == 1 and existed
    try:
        ks.create("pnc", "carol")
        assert False, "expected capacity error"
    except KeyError:
        pass
    assert ks.create("orset", "carol") == 0  # independent per type


def test_orset_capture_replay_commutes_model_level():
    """Regression: remove must tombstone what the *origin observed*
    (captured tag set), not whatever is present at apply time, so that
    replicas applying [add, remove] vs [remove, add] converge."""
    origin = orset.init(1, 8)
    origin = orset.apply_ops(origin, base.make_op_batch(
        op=[orset.OP_ADD], key=[0], a0=[7], a1=[0], a2=[1]))
    rm = orset.prepare_ops(
        origin, base.make_op_batch(op=[orset.OP_REMOVE], key=[0], a0=[7]))
    assert rm["rm_rep"][0, 0] == 0 and rm["rm_ctr"][0, 0] == 1
    # an UNOBSERVED concurrent add (fresh tag (0,2)) must survive the
    # remove in either application order (add-wins)
    add2 = base.make_op_batch(op=[orset.OP_ADD], key=[0], a0=[7], a1=[0], a2=[2])

    fresh = orset.init(1, 8)
    a_then_r = orset.apply_ops(orset.apply_ops(fresh, add2), rm)
    r_then_a = orset.apply_ops(orset.apply_ops(fresh, rm), add2)
    assert bool(orset.contains(a_then_r, 0, 7))
    assert bool(orset.contains(r_then_a, 0, 7))


def test_orset_late_observed_add_cannot_resurrect():
    """The round-1 advisor's divergence repro: an add the remove's origin
    HAD observed reaches another node only after the remove. The captured
    tombstone record must kill it on arrival; replicas converge dead."""
    add1 = base.make_op_batch(op=[orset.OP_ADD], key=[0], a0=[7], a1=[0], a2=[1])
    origin = orset.apply_ops(orset.init(1, 8), add1)
    rm = orset.prepare_ops(
        origin, base.make_op_batch(op=[orset.OP_REMOVE], key=[0], a0=[7]))

    x = orset.apply_ops(orset.apply_ops(orset.init(1, 8), add1), rm)
    y = orset.apply_ops(orset.apply_ops(orset.init(1, 8), rm), add1)
    assert not bool(orset.contains(x, 0, 7))
    assert not bool(orset.contains(y, 0, 7))  # round-1 code failed here
    merged = orset.merge(x, y)
    assert not bool(orset.contains(merged, 0, 7))
    # and the join itself agrees regardless of merge direction
    m2 = orset.merge(y, x)
    for f in merged:
        np.testing.assert_array_equal(np.asarray(merged[f]), np.asarray(m2[f]))


def test_safekv_concurrent_add_remove_no_divergence():
    """The review repro: concurrent ADD and REMOVE with skewed delivery
    must leave all replicas agreeing once fully synced."""
    kv = SafeKV(DagConfig(N, W), orset.SPEC, ops_per_block=B,
                num_keys=2, capacity=16)
    minters = [TagMinter(v) for v in range(N)]
    # node 0 adds elem 42; everyone learns it
    op = np.zeros((N, B), np.int32); key = np.zeros((N, B), np.int32)
    a0 = np.zeros((N, B), np.int32); a1 = np.zeros((N, B), np.int32)
    a2 = np.zeros((N, B), np.int32)
    t = minters[0].mint_many(1)[0]
    op[0, 0], key[0, 0], a0[0, 0] = orset.OP_ADD, 1, 42
    a1[0, 0], a2[0, 0] = t
    kv.submit(base.make_op_batch(op=op, key=key, a0=a0, a1=a1, a2=a2))
    kv.tick(); kv.tick()
    # concurrent: node 1 removes 42, node 0 re-adds with a fresh tag
    op2 = np.zeros((N, B), np.int32); key2 = np.zeros((N, B), np.int32)
    a02 = np.zeros((N, B), np.int32); a12 = np.zeros((N, B), np.int32)
    a22 = np.zeros((N, B), np.int32)
    t2 = minters[0].mint_many(1)[0]
    op2[0, 0], key2[0, 0], a02[0, 0] = orset.OP_ADD, 1, 42
    a12[0, 0], a22[0, 0] = t2
    op2[1, 0], key2[1, 0], a02[1, 0] = orset.OP_REMOVE, 1, 42
    kv.submit(base.make_op_batch(op=op2, key=key2, a0=a02, a1=a12, a2=a22))
    import jax.numpy as jnp
    crash = jnp.asarray([True, True, True, False])
    kv.tick(active=crash)   # one degraded round
    for _ in range(4):
        kv.tick()           # full recovery + drain
    prosp = np.asarray(kv.query_prospective("contains", 1, 42))
    stable = np.asarray(kv.query_stable("contains", 1, 42))
    assert (prosp == prosp[0]).all(), prosp
    assert (stable == stable[0]).all(), stable
    assert prosp[0]  # add-wins: the fresh re-add tag survives


def test_safe_acks_accumulate_until_drained():
    """Safe acks survive hosts that poll less often than every tick:
    they accumulate across ticks and clear only on drain (the reference
    tracks per-(client, seq) until the notifier fires,
    SafeCRDTManager.cs:108-160)."""
    kv = make_kv()
    kv.submit(pnc_ops([[(0, 1)], [(1, 2)], [], []]),
              safe=np.asarray([[True] + [False] * (B - 1),
                               [True] + [False] * (B - 1),
                               [False] * B, [False] * B]))
    for _ in range(2 * W):      # no drain in between
        kv.tick()
    acks = kv.safe_acks()
    assert acks.sum() == 2      # both safe ops acked, none lost
    assert kv.safe_acks().sum() == 2   # peeking does not consume
    drained = kv.drain_safe_acks()
    np.testing.assert_array_equal(drained, acks)
    assert kv.drain_safe_acks().sum() == 0   # drained clear
