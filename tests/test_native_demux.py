"""Native zero-GIL shard demux (ISSUE 17): the server routes decoded
batch-frame columns (and per-op ClientMessages) into per-shard native
rings on its io thread, keyed by the same FNV-1a shard_of as the Python
router. Contracts under test:

- ``janus_shard_of`` is byte-for-byte ``runtime.keyspace.shard_of``
  over randomized type codes / key names / shard counts (plus pinned
  oracle values, so BOTH implementations drifting together still
  fails);
- ring routing is bit-identical to Python shard_of end to end: every
  op drained from ring K names a key whose shard_of is K, columns
  (op/params/t0_ns) intact, router queue untouched by data ops;
- a sharded service produces the same final CRDT state with the native
  demux as with the Python router fallback and as unsharded — over
  randomized keys, exercising the worker's (home, key) -> slot
  fast-slot priming on native-drained columns;
- t0_ns propagation: stamped v2 frames and unstamped v1 frames land in
  the SLO ledger identically (same replied / e2e-sample accounting)
  whether ops arrive via the native ring or the Python router.
"""
import json
import socket
import struct
import time

import numpy as np
import pytest

from janus_tpu.net import JanusClient, JanusConfig, JanusService, TypeConfig
from janus_tpu.net.client import BatchSender, encode_client_message, frame0
from janus_tpu.runtime.keyspace import shard_of

pytestmark = pytest.mark.usefixtures("native_lib")


# -- shard_of parity -------------------------------------------------------

# pinned oracles: independent of BOTH implementations, so a bug that
# changes the hash in lockstep (e.g. editing the seed in both files)
# still trips
_ORACLE = [
    (("pnc", "o0", 2), 0), (("pnc", "o1", 2), 1),
    (("pnc", "o2", 2), 0), (("pnc", "o3", 2), 1),
    (("pnc", "o0", 4), 2), (("pnc", "o1", 4), 1),
    (("pnc", "o2", 4), 0), (("pnc", "o3", 4), 3),
    (("orset", "o0", 4), 2), (("pnc", "user:42", 7), 3),
]


def test_shard_of_oracle_values():
    from janus_tpu.net.binding import native_shard_of
    for (tc, key, n), want in _ORACLE:
        assert shard_of(tc, key, n) == want, (tc, key, n)
        assert native_shard_of(tc, key, n) == want, (tc, key, n)


def test_shard_of_native_parity_randomized(rng):
    from janus_tpu.net.binding import native_shard_of
    codes = ["pnc", "orset", "lww", "tpset", "mvr", "x", "stats"]
    alphabet = ("abcdefghijklmnopqrstuvwxyz"
                "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789:_-./")
    for _ in range(3000):
        tc = codes[int(rng.integers(len(codes)))]
        klen = int(rng.integers(1, 40))
        key = "".join(alphabet[int(i)]
                      for i in rng.integers(0, len(alphabet), klen))
        n = int(rng.integers(1, 64))
        assert native_shard_of(tc, key, n) == shard_of(tc, key, n), \
            (tc, key, n)
    # degenerate shard counts collapse to shard 0
    assert native_shard_of("pnc", "k", 1) == 0
    assert native_shard_of("pnc", "k", 0) == 0


# -- ring routing vs Python shard_of ---------------------------------------

def _v2_frame(seq0, type_code, keys, key_idx, op, p0, t0_ns):
    from janus_tpu.net.client import encode_batch_frame
    m = len(key_idx)
    return encode_batch_frame(
        seq0, type_code, keys,
        np.asarray(key_idx, np.int32),
        np.full(m, ord(op), np.uint8),
        np.zeros(m, np.uint8),
        np.asarray(p0, np.int64), t0_ns=t0_ns)


def _v1_frame(seq0, type_code, keys, key_idx, op, p0):
    """Hand-built version-1 batch frame: no t0_ns in the header, so
    every op counts as unstamped (old clients)."""
    tc = type_code.encode()
    head = bytearray([0x00, 1, len(tc)])
    head += tc
    head += struct.pack("<I", seq0 & 0xFFFFFFFF)
    head += struct.pack("<H", len(keys))
    for k in keys:
        kb = k.encode()
        head += struct.pack("<H", len(kb)) + kb
    m = len(key_idx)
    head += struct.pack("<I", m)
    head += np.asarray(key_idx, np.int32).tobytes()
    head += np.full(m, ord(op), np.uint8).tobytes()
    head += np.zeros(m, np.uint8).tobytes()
    head += np.asarray(p0, np.int64).tobytes()
    return bytes(head)


def test_ring_routing_bit_identical_to_python(rng):
    """Drain every ring of a raw NativeServer and check each op landed
    on exactly the ring Python shard_of names, with columns intact."""
    from janus_tpu.net.binding import NativeServer
    srv = NativeServer("127.0.0.1", 0, 32)
    shards = 4
    keys = [f"k{int(rng.integers(1 << 30)):x}" for _ in range(48)]
    try:
        tids = {tc: srv.register_type(tc, 64) for tc in ("pnc", "orset")}
        srv.set_shards(shards)
        port = srv.start()
        m = 512
        idx = rng.integers(0, len(keys), m).astype(np.int32)
        p0 = rng.integers(1, 100, m).astype(np.int64)
        with socket.create_connection(("127.0.0.1", port)) as sk:
            # one stamped v2 frame per type (same key dict, so slot i
            # of either type is keys[i]) + a few per-op messages, which
            # take the protobuf handle_payload path into the same rings
            sk.sendall(frame0(_v2_frame(1, "pnc", keys, idx, "i", p0,
                                        t0_ns=123456789)))
            sk.sendall(frame0(_v2_frame(m + 1, "orset", keys, idx, "a",
                                        p0, t0_ns=987654321)))
            per_op = 16
            for j in range(per_op):
                sk.sendall(frame0(encode_client_message(
                    2 * m + 1 + j, keys[j], "pnc", "i", ["5"],
                    t0_ns=42)))
            total = 2 * m + per_op
            deadline = time.time() + 30
            while time.time() < deadline:
                if sum(srv.shard_depth(s) for s in range(shards)) >= total:
                    break
                time.sleep(0.02)
            drained = 0
            for s in range(shards):
                cols = srv.poll_batch_shard(s, total)
                n = len(cols["client_tag"])
                drained += n
                assert srv.shard_hwm(s) >= n
                for i in range(n):
                    tc = ("pnc" if int(cols["type_id"][i]) == tids["pnc"]
                          else "orset")
                    key = keys[int(cols["key_slot"][i])]
                    assert shard_of(tc, key, shards) == s, (tc, key, s)
                    assert int(cols["t0_ns"][i]) in (123456789, 987654321,
                                                     42)
                assert len(srv.poll_batch_shard(s, 16)["client_tag"]) == 0
            assert drained == total
            # data ops never touched the router queue
            assert srv.router_depth() == 0
            assert len(srv.poll_batch(64)["client_tag"]) == 0
    finally:
        srv.close()


def test_pinned_type_stays_on_router_queue():
    from janus_tpu.net.binding import NativeServer
    srv = NativeServer("127.0.0.1", 0, 8)
    try:
        tid = srv.register_type("stats", 4)
        srv.set_shards(2)
        srv.pin_type_router(tid)
        port = srv.start()
        with socket.create_connection(("127.0.0.1", port)) as sk:
            sk.sendall(frame0(encode_client_message(1, "_", "stats", "g")))
            deadline = time.time() + 30
            while time.time() < deadline and srv.router_depth() < 1:
                time.sleep(0.02)
        assert srv.router_depth() == 1
        assert srv.shard_depth(0) == 0 and srv.shard_depth(1) == 0
        cols = srv.poll_batch(16)
        assert len(cols["client_tag"]) == 1
        assert int(cols["type_id"][0]) == tid
    finally:
        srv.close()


# -- service-level state parity (fast-slot priming rides along) ------------

def _mk_service(shards: int, native: bool) -> JanusService:
    return JanusService(JanusConfig(
        num_nodes=4, window=8, ops_per_block=16, shards=shards,
        native_demux=native,
        types=(TypeConfig("pnc", {"num_keys": 64}),)))


def _drive_frames(svc: JanusService, port: int, keys, idx, p0,
                  want) -> dict:
    out = {}
    # gate on ledger replied DELTAS, not just pending==0: the stats
    # check alone can pass before the io thread has even parsed the
    # frame (50 ms poll cadence), and the frame rides its own
    # connection so read-your-writes doesn't order the reads behind it
    done = svc._slo_snapshot()["replied_total"]
    with JanusClient("127.0.0.1", port, timeout=120) as c:
        for k in keys:
            assert c.request("pnc", k, "s", timeout=120)["response"] != "err"
        sender = BatchSender("127.0.0.1", port)
        m = sender.send_frame("pnc", keys, idx, "i", p0=p0)
        deadline = time.time() + 120
        while time.time() < deadline:
            st = json.loads(c.request("stats", "_", "g",
                                      timeout=120)["result"])
            if (st["types"]["pnc"]["pending_ops"] == 0
                    and st.get("inbox_depth", 0) == 0
                    and svc._slo_snapshot()["replied_total"]
                    >= done + len(keys) + m):
                break
            time.sleep(0.05)
        sender.close()
        # unsafe increments from the sender's connection become visible
        # to THIS connection's prospective reads only after delta
        # propagation across the emulated cluster — poll to convergence
        # (a routing/priming bug never converges; a propagation delay
        # does)
        while time.time() < deadline:
            out = {k: int(c.request("pnc", k, "gp",
                                    timeout=120)["result"])
                   for k in keys}
            if all(out[k] == want.get(k, 0) for k in keys):
                break
            time.sleep(0.1)
    return out


def test_native_demux_state_matches_python_router_and_unsharded(rng):
    """Randomized keys through three arms — unsharded, Python router,
    native demux — must agree exactly. The native arm's columns reach
    the worker pre-routed, so its _ingest_columnar primes (home, key)
    -> slot fast-slots from ring-drained chunks; a priming bug shows up
    as a state divergence here."""
    keys = sorted({f"k{int(rng.integers(1 << 20)):x}" for _ in range(24)})
    m = 768
    idx = rng.integers(0, len(keys), m).astype(np.int32)
    p0 = rng.integers(1, 50, m).astype(np.int64)
    want = {}
    for i, a in zip(idx.tolist(), p0.tolist()):
        want[keys[i]] = want.get(keys[i], 0) + a
    results = {}
    for arm, (shards, native) in {
            "unsharded": (1, True), "pyrouter": (4, False),
            "native": (4, True)}.items():
        svc = _mk_service(shards, native)
        port = svc.start()
        try:
            results[arm] = _drive_frames(svc, port, keys, idx, p0, want)
        finally:
            svc.stop()
    for k in keys:
        assert results["native"][k] == want.get(k, 0), k
        assert results["native"][k] == results["pyrouter"][k], k
        assert results["native"][k] == results["unsharded"][k], k


# -- t0_ns propagation into the SLO ledger ----------------------------------

def _slo_invariants(snap: dict, base: dict) -> dict:
    """The run-deterministic part of a merged /slo snapshot (latency
    buckets vary run to run; counts must not), as DELTAS against a
    post-start baseline — ledger counters live in the process-wide
    metrics registry under scope _s{K}, so successive service
    instances in one test process accumulate into the same counters."""
    return {
        "offered": snap["offered"] - base["offered"],
        "admitted": snap["admitted"] - base["admitted"],
        "shed": snap["shed"] - base["shed"],
        "replied_total": snap["replied_total"] - base["replied_total"],
        "classes": {
            c: {"replied": v["replied"] - base["classes"][c]["replied"],
                "e2e_samples": (v["e2e_samples"]
                                - base["classes"][c]["e2e_samples"])}
            for c, v in snap["classes"].items()},
    }


def _drive_slo(native: bool, stamped: bool):
    """4 stamped creates + 96 batched unsafe increments (stamped v2 or
    unstamped v1 frame) + stamped convergence reads; returns the
    ledger's invariant counts plus the read count (reads are ledger-
    visible, so the caller normalizes them out before comparing)."""
    keys = [f"o{k}" for k in range(4)]
    m = 96
    idx = np.asarray([i % 4 for i in range(m)], np.int32)
    p0 = np.asarray([1 + (i % 5) for i in range(m)], np.int64)
    svc = _mk_service(2, native)
    port = svc.start()
    try:
        base = svc._slo_snapshot()  # registry counters persist across
        done = base["replied_total"]  # instances in one process
        with JanusClient("127.0.0.1", port, timeout=120) as c:
            for k in keys:
                assert c.request("pnc", k, "s",
                                 timeout=120)["response"] != "err"
            with socket.create_connection(("127.0.0.1", port)) as sk:
                if stamped:
                    payload = _v2_frame(1, "pnc", keys, idx, "i", p0,
                                        t0_ns=time.monotonic_ns())
                else:
                    payload = _v1_frame(1, "pnc", keys, idx, "i", p0)
                sk.sendall(frame0(payload))
                want = {keys[i]: 0 for i in range(4)}
                for i, a in zip(idx.tolist(), p0.tolist()):
                    want[keys[i]] += a
                # the frame rides its own connection, so read-your-
                # writes does NOT order the reads behind it — wait for
                # full ingest (and its acks) before reading
                deadline = time.time() + 120
                while time.time() < deadline:
                    st = json.loads(c.request("stats", "_", "g",
                                              timeout=120)["result"])
                    if (st["types"]["pnc"]["pending_ops"] == 0
                            and st["inbox_depth"] == 0
                            and svc._slo_snapshot()["replied_total"]
                            >= done + 4 + m):
                        break
                    time.sleep(0.05)
                # unsafe increments become visible to this connection
                # only after delta propagation across the emulated
                # cluster — poll reads to convergence, counting them
                n_reads = 0
                while time.time() < deadline:
                    got = {}
                    for k in keys:
                        got[k] = int(c.request("pnc", k, "gp",
                                               timeout=120)["result"])
                        n_reads += 1
                    if got == want:
                        break
                    time.sleep(0.1)
                assert got == want, (got, want)
        deadline = time.time() + 120
        snap = svc._slo_snapshot()
        while (snap["replied_total"] < done + 4 + m + n_reads
               and time.time() < deadline):
            time.sleep(0.05)
            snap = svc._slo_snapshot()
    finally:
        svc.stop()
    out = _slo_invariants(snap, base)
    assert out["replied_total"] == 4 + m + n_reads
    return out, n_reads


def _minus_reads(inv: dict, n_reads: int) -> dict:
    """Normalize the convergence reads out of the invariant counts —
    gp reads are unsafe-class, always stamped, and their number varies
    with propagation timing."""
    out = json.loads(json.dumps(inv))
    out["offered"] -= n_reads
    out["admitted"] -= n_reads
    out["replied_total"] -= n_reads
    out["classes"]["unsafe"]["replied"] -= n_reads
    out["classes"]["unsafe"]["e2e_samples"] -= n_reads
    return out


@pytest.mark.parametrize("stamped", [True, False],
                         ids=["v2_stamped", "v1_unstamped"])
def test_t0_propagation_native_matches_python_router(stamped):
    via_native, n_nat = _drive_slo(native=True, stamped=stamped)
    via_python, n_py = _drive_slo(native=False, stamped=stamped)
    nat, py = _minus_reads(via_native, n_nat), _minus_reads(via_python, n_py)
    assert nat == py
    # absolute accounting: creates are safe class (4, stamped); the
    # frame's 96 unsafe increments sample e2e iff the frame was v2
    assert nat == {
        "offered": 4 + 96, "admitted": 4 + 96, "shed": 0,
        "replied_total": 4 + 96,
        "classes": {
            "unsafe": {"replied": 96,
                       "e2e_samples": 96 if stamped else 0},
            "safe": {"replied": 4, "e2e_samples": 4},
            "stable": {"replied": 0, "e2e_samples": 0},
        },
    }
