"""Slot-set union kernel vs a Python dict reference model.

Property tests for the join laws (commutativity, associativity,
idempotence) that the reference asserts per-type in MergeSharp.Tests
(ORSetTests.cs, LWWSetTests.cs) — here proven once at the kernel level.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from janus_tpu.ops import SENTINEL, make_slots, row_find, row_insert, row_upsert, slot_union


def or_combine(p, q):
    return {"removed": p["removed"] | q["removed"], "elem": p["elem"]}


def random_slots(rng, cap, n):
    """Random OR-Set-shaped slot set: key=(tag,), payload elem + removed."""
    tags = rng.choice(10_000, size=n, replace=False)
    tag = np.full(cap, int(np.iinfo(np.int32).max), np.int32)
    elem = np.zeros(cap, np.int32)
    removed = np.zeros(cap, bool)
    valid = np.zeros(cap, bool)
    tag[:n] = tags
    elem[:n] = rng.integers(0, 50, n)
    removed[:n] = rng.integers(0, 2, n)
    valid[:n] = True
    return {
        "tag": jnp.asarray(tag),
        "elem": jnp.asarray(elem),
        "removed": jnp.asarray(removed),
        "valid": jnp.asarray(valid),
    }


def to_dict(s):
    """Slot set -> {tag: (elem, removed)} for comparison."""
    out = {}
    v = np.asarray(s["valid"])
    for i in np.nonzero(v)[0]:
        out[int(np.asarray(s["tag"])[i])] = (
            int(np.asarray(s["elem"])[i]),
            bool(np.asarray(s["removed"])[i]),
        )
    return out


def dict_union(da, db):
    out = dict(da)
    for t, (e, r) in db.items():
        if t in out:
            out[t] = (out[t][0], out[t][1] or r)
        else:
            out[t] = (e, r)
    return out


@pytest.mark.parametrize("seed", range(5))
def test_union_matches_reference_model(seed):
    rng = np.random.default_rng(seed)
    a = random_slots(rng, 32, rng.integers(0, 20))
    b = random_slots(rng, 32, rng.integers(0, 20))
    u, ovf = slot_union(a, b, ("tag",), or_combine, capacity=64)
    assert int(ovf) == 0
    assert to_dict(u) == dict_union(to_dict(a), to_dict(b))


@pytest.mark.parametrize("seed", range(3))
def test_union_laws(seed):
    rng = np.random.default_rng(100 + seed)
    a = random_slots(rng, 16, 10)
    b = random_slots(rng, 16, 8)
    c = random_slots(rng, 16, 5)
    u_ab, _ = slot_union(a, b, ("tag",), or_combine, capacity=48)
    u_ba, _ = slot_union(b, a, ("tag",), or_combine, capacity=48)
    assert to_dict(u_ab) == to_dict(u_ba)  # commutative
    u_aa, _ = slot_union(a, a, ("tag",), or_combine, capacity=48)
    assert to_dict(u_aa) == to_dict(a)  # idempotent
    l, _ = slot_union(u_ab, c, ("tag",), or_combine, capacity=48)
    u_bc, _ = slot_union(b, c, ("tag",), or_combine, capacity=48)
    r, _ = slot_union(a, u_bc, ("tag",), or_combine, capacity=48)
    assert to_dict(l) == to_dict(r)  # associative


def test_union_duplicate_tag_folds_tombstone():
    """A tag removed on one side stays removed after union (tombstone OR)."""
    a = {
        "tag": jnp.array([5, SENTINEL], jnp.int32),
        "elem": jnp.array([7, 0], jnp.int32),
        "removed": jnp.array([False, False]),
        "valid": jnp.array([True, False]),
    }
    b = {
        "tag": jnp.array([5, 9], jnp.int32),
        "elem": jnp.array([7, 8], jnp.int32),
        "removed": jnp.array([True, False]),
        "valid": jnp.array([True, True]),
    }
    u, _ = slot_union(a, b, ("tag",), or_combine, capacity=4)
    assert to_dict(u) == {5: (7, True), 9: (8, False)}


def test_union_pads_to_requested_capacity():
    """capacity larger than the concatenated inputs must pad, not shrink."""
    rng = np.random.default_rng(21)
    a, b = random_slots(rng, 4, 3), random_slots(rng, 4, 2)
    u, ovf = slot_union(a, b, ("tag",), or_combine, capacity=16)
    assert all(u[f].shape[-1] == 16 for f in u)
    assert int(ovf) == 0
    assert to_dict(u) == dict_union(to_dict(a), to_dict(b))


def test_union_overflow_reported():
    rng = np.random.default_rng(7)
    a = random_slots(rng, 16, 16)
    b = random_slots(np.random.default_rng(8), 16, 16)
    u, ovf = slot_union(a, b, ("tag",), or_combine, capacity=16)
    kept = len(dict_union(to_dict(a), to_dict(b)))
    assert int(ovf) == max(0, kept - 16)
    assert int(np.asarray(u["valid"]).sum()) == min(16, kept)


def test_union_batched_leading_axes():
    """Union batches over leading (replica, key) axes without vmap."""
    rng = np.random.default_rng(3)
    rows_a = [random_slots(rng, 8, rng.integers(0, 6)) for _ in range(6)]
    rows_b = [random_slots(rng, 8, rng.integers(0, 6)) for _ in range(6)]
    stack = lambda rows: {
        f: jnp.stack([r[f] for r in rows]).reshape(2, 3, 8) for f in rows[0]
    }
    u, _ = slot_union(stack(rows_a), stack(rows_b), ("tag",), or_combine, capacity=16)
    flat = {f: np.asarray(u[f]).reshape(6, 16) for f in u}
    for i in range(6):
        got = to_dict({f: jnp.asarray(flat[f][i]) for f in u})
        assert got == dict_union(to_dict(rows_a[i]), to_dict(rows_b[i]))


def test_union_jits():
    rng = np.random.default_rng(11)
    a, b = random_slots(rng, 16, 9), random_slots(rng, 16, 4)
    f = jax.jit(lambda x, y: slot_union(x, y, ("tag",), or_combine, capacity=32))
    u, _ = f(a, b)
    assert to_dict(u) == dict_union(to_dict(a), to_dict(b))


def test_row_find_insert_upsert():
    row = make_slots(4, {"elem": jnp.int32, "ts": jnp.int32})
    found, _ = row_find(row, ("elem",), (jnp.int32(3),))
    assert not bool(found)
    row = row_insert(row, {"elem": jnp.int32(3), "ts": jnp.int32(10)})
    found, idx = row_find(row, ("elem",), (jnp.int32(3),))
    assert bool(found) and int(row["ts"][idx]) == 10
    # upsert existing folds with max; new key inserts
    comb = lambda old, new: {"ts": jnp.maximum(old["ts"], new["ts"])}
    row = row_upsert(row, ("elem",), (jnp.int32(3),), {"ts": jnp.int32(7)}, comb)
    row = row_upsert(row, ("elem",), (jnp.int32(5),), {"ts": jnp.int32(2)}, comb)
    _, i3 = row_find(row, ("elem",), (jnp.int32(3),))
    f5, i5 = row_find(row, ("elem",), (jnp.int32(5),))
    assert int(row["ts"][i3]) == 10 and bool(f5) and int(row["ts"][i5]) == 2
    # disabled upsert is a no-op
    row2 = row_upsert(row, ("elem",), (jnp.int32(9),), {"ts": jnp.int32(1)}, comb, enabled=False)
    np.testing.assert_array_equal(np.asarray(row2["valid"]), np.asarray(row["valid"]))
