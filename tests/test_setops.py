"""Slot-set union kernel vs a Python dict reference model.

Property tests for the join laws (commutativity, associativity,
idempotence) that the reference asserts per-type in MergeSharp.Tests
(ORSetTests.cs, LWWSetTests.cs) — here proven once at the kernel level.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from janus_tpu.ops import SENTINEL, make_slots, row_find, row_insert, row_upsert, slot_union
from janus_tpu.ops.setops import mark_members


def or_combine(p, q):
    return {"removed": p["removed"] | q["removed"], "elem": p["elem"]}


def random_slots(rng, cap, n):
    """Random OR-Set-shaped slot set: key=(tag,), payload elem + removed."""
    tags = rng.choice(10_000, size=n, replace=False)
    tag = np.full(cap, int(np.iinfo(np.int32).max), np.int32)
    elem = np.zeros(cap, np.int32)
    removed = np.zeros(cap, bool)
    valid = np.zeros(cap, bool)
    tag[:n] = tags
    elem[:n] = rng.integers(0, 50, n)
    removed[:n] = rng.integers(0, 2, n)
    valid[:n] = True
    return {
        "tag": jnp.asarray(tag),
        "elem": jnp.asarray(elem),
        "removed": jnp.asarray(removed),
        "valid": jnp.asarray(valid),
    }


def to_dict(s):
    """Slot set -> {tag: (elem, removed)} for comparison."""
    out = {}
    v = np.asarray(s["valid"])
    for i in np.nonzero(v)[0]:
        out[int(np.asarray(s["tag"])[i])] = (
            int(np.asarray(s["elem"])[i]),
            bool(np.asarray(s["removed"])[i]),
        )
    return out


def dict_union(da, db):
    out = dict(da)
    for t, (e, r) in db.items():
        if t in out:
            out[t] = (out[t][0], out[t][1] or r)
        else:
            out[t] = (e, r)
    return out


@pytest.mark.parametrize("seed", range(5))
def test_union_matches_reference_model(seed):
    rng = np.random.default_rng(seed)
    a = random_slots(rng, 32, rng.integers(0, 20))
    b = random_slots(rng, 32, rng.integers(0, 20))
    u, ovf = slot_union(a, b, ("tag",), or_combine, capacity=64)
    assert int(ovf) == 0
    assert to_dict(u) == dict_union(to_dict(a), to_dict(b))


@pytest.mark.parametrize("seed", range(3))
def test_union_laws(seed):
    rng = np.random.default_rng(100 + seed)
    a = random_slots(rng, 16, 10)
    b = random_slots(rng, 16, 8)
    c = random_slots(rng, 16, 5)
    u_ab, _ = slot_union(a, b, ("tag",), or_combine, capacity=48)
    u_ba, _ = slot_union(b, a, ("tag",), or_combine, capacity=48)
    assert to_dict(u_ab) == to_dict(u_ba)  # commutative
    u_aa, _ = slot_union(a, a, ("tag",), or_combine, capacity=48)
    assert to_dict(u_aa) == to_dict(a)  # idempotent
    l, _ = slot_union(u_ab, c, ("tag",), or_combine, capacity=48)
    u_bc, _ = slot_union(b, c, ("tag",), or_combine, capacity=48)
    r, _ = slot_union(a, u_bc, ("tag",), or_combine, capacity=48)
    assert to_dict(l) == to_dict(r)  # associative


def test_union_duplicate_tag_folds_tombstone():
    """A tag removed on one side stays removed after union (tombstone OR)."""
    a = {
        "tag": jnp.array([5, SENTINEL], jnp.int32),
        "elem": jnp.array([7, 0], jnp.int32),
        "removed": jnp.array([False, False]),
        "valid": jnp.array([True, False]),
    }
    b = {
        "tag": jnp.array([5, 9], jnp.int32),
        "elem": jnp.array([7, 8], jnp.int32),
        "removed": jnp.array([True, False]),
        "valid": jnp.array([True, True]),
    }
    u, _ = slot_union(a, b, ("tag",), or_combine, capacity=4)
    assert to_dict(u) == {5: (7, True), 9: (8, False)}


def test_union_pads_to_requested_capacity():
    """capacity larger than the concatenated inputs must pad, not shrink."""
    rng = np.random.default_rng(21)
    a, b = random_slots(rng, 4, 3), random_slots(rng, 4, 2)
    u, ovf = slot_union(a, b, ("tag",), or_combine, capacity=16)
    assert all(u[f].shape[-1] == 16 for f in u)
    assert int(ovf) == 0
    assert to_dict(u) == dict_union(to_dict(a), to_dict(b))


def test_union_overflow_reported():
    rng = np.random.default_rng(7)
    a = random_slots(rng, 16, 16)
    b = random_slots(np.random.default_rng(8), 16, 16)
    u, ovf = slot_union(a, b, ("tag",), or_combine, capacity=16)
    kept = len(dict_union(to_dict(a), to_dict(b)))
    assert int(ovf) == max(0, kept - 16)
    assert int(np.asarray(u["valid"]).sum()) == min(16, kept)


def test_union_batched_leading_axes():
    """Union batches over leading (replica, key) axes without vmap."""
    rng = np.random.default_rng(3)
    rows_a = [random_slots(rng, 8, rng.integers(0, 6)) for _ in range(6)]
    rows_b = [random_slots(rng, 8, rng.integers(0, 6)) for _ in range(6)]
    stack = lambda rows: {
        f: jnp.stack([r[f] for r in rows]).reshape(2, 3, 8) for f in rows[0]
    }
    u, _ = slot_union(stack(rows_a), stack(rows_b), ("tag",), or_combine, capacity=16)
    flat = {f: np.asarray(u[f]).reshape(6, 16) for f in u}
    for i in range(6):
        got = to_dict({f: jnp.asarray(flat[f][i]) for f in u})
        assert got == dict_union(to_dict(rows_a[i]), to_dict(rows_b[i]))


def test_union_jits():
    rng = np.random.default_rng(11)
    a, b = random_slots(rng, 16, 9), random_slots(rng, 16, 4)
    f = jax.jit(lambda x, y: slot_union(x, y, ("tag",), or_combine, capacity=32))
    u, _ = f(a, b)
    assert to_dict(u) == dict_union(to_dict(a), to_dict(b))


def test_row_find_insert_upsert():
    row = make_slots(4, {"elem": jnp.int32, "ts": jnp.int32})
    found, _ = row_find(row, ("elem",), (jnp.int32(3),))
    assert not bool(found)
    row = row_insert(row, {"elem": jnp.int32(3), "ts": jnp.int32(10)})
    found, idx = row_find(row, ("elem",), (jnp.int32(3),))
    assert bool(found) and int(row["ts"][idx]) == 10
    # upsert existing folds with max; new key inserts
    comb = lambda old, new: {"ts": jnp.maximum(old["ts"], new["ts"])}
    row = row_upsert(row, ("elem",), (jnp.int32(3),), {"ts": jnp.int32(7)}, comb)
    row = row_upsert(row, ("elem",), (jnp.int32(5),), {"ts": jnp.int32(2)}, comb)
    _, i3 = row_find(row, ("elem",), (jnp.int32(3),))
    f5, i5 = row_find(row, ("elem",), (jnp.int32(5),))
    assert int(row["ts"][i3]) == 10 and bool(f5) and int(row["ts"][i5]) == 2
    # disabled upsert is a no-op
    row2 = row_upsert(row, ("elem",), (jnp.int32(9),), {"ts": jnp.int32(1)}, comb, enabled=False)
    np.testing.assert_array_equal(np.asarray(row2["valid"]), np.asarray(row["valid"]))


def test_row_insert_stats_counts_drops():
    """A full row drops the insert AND counts it; disabled or successful
    inserts count nothing."""
    row = make_slots(2, {"elem": jnp.int32})
    stats = {"slots_dropped": jnp.int32(0)}
    row = row_insert(row, {"elem": jnp.int32(1)}, stats=stats)
    row = row_insert(row, {"elem": jnp.int32(2)}, stats=stats)
    assert int(stats["slots_dropped"]) == 0
    row = row_insert(row, {"elem": jnp.int32(3)}, stats=stats)  # full: drop
    assert int(stats["slots_dropped"]) == 1
    row = row_insert(row, {"elem": jnp.int32(4)}, enabled=False, stats=stats)
    assert int(stats["slots_dropped"]) == 1  # disabled lane never counts
    assert sorted(np.asarray(row["elem"])[np.asarray(row["valid"])]) == [1, 2]


def test_row_upsert_stats_counts_only_absent_key_drops():
    """Folding into an existing key of a FULL row is not a drop; an
    absent key hitting a full row is."""
    comb = lambda old, new: {"ts": jnp.maximum(old["ts"], new["ts"])}
    row = make_slots(2, {"elem": jnp.int32, "ts": jnp.int32})
    stats = {"slots_dropped": jnp.int32(0)}
    for e in (1, 2):
        row = row_upsert(row, ("elem",), (jnp.int32(e),),
                         {"ts": jnp.int32(e)}, comb, stats=stats)
    row = row_upsert(row, ("elem",), (jnp.int32(1),), {"ts": jnp.int32(9)},
                     comb, stats=stats)  # fold, row full: NOT a drop
    assert int(stats["slots_dropped"]) == 0
    row = row_upsert(row, ("elem",), (jnp.int32(7),), {"ts": jnp.int32(1)},
                     comb, stats=stats)  # absent key, row full: drop
    assert int(stats["slots_dropped"]) == 1
    _, i1 = row_find(row, ("elem",), (jnp.int32(1),))
    assert int(row["ts"][i1]) == 9


# ---------------------------------------------------------------------------
# mark_members edge cases (the membership primitive compaction fences use)
# ---------------------------------------------------------------------------

def _mark_ref(a_keys, b_keys, b_valid):
    """O(M*T) reference model."""
    k1a, k2a = (np.asarray(k) for k in a_keys)
    k1b, k2b = (np.asarray(k) for k in b_keys)
    bv = np.asarray(b_valid)
    live = {(int(k1b[j]), int(k2b[j])) for j in np.nonzero(bv)[0]}
    return np.array([(int(k1a[i]), int(k2a[i])) in live
                     for i in range(k1a.shape[0])])


def test_mark_members_empty_b_all_invalid():
    """b_valid all False: nothing can match, even on exact key equality."""
    a = (jnp.array([3, 5, 7], jnp.int32), jnp.array([1, 1, 1], jnp.int32))
    b = (jnp.array([3, 5], jnp.int32), jnp.array([1, 1], jnp.int32))
    got = mark_members(a, b, jnp.zeros(2, bool))
    np.testing.assert_array_equal(np.asarray(got), np.zeros(3, bool))


def test_mark_members_all_invalid_a_rows():
    """A slots keyed SENTINEL (invalid) never match — not other SENTINEL
    A rows, and not SENTINEL-masked invalid B entries."""
    a = (jnp.full(4, SENTINEL, jnp.int32), jnp.full(4, SENTINEL, jnp.int32))
    b = (jnp.array([SENTINEL, 2], jnp.int32), jnp.array([SENTINEL, 2], jnp.int32))
    got = mark_members(a, b, jnp.array([False, True]))
    np.testing.assert_array_equal(np.asarray(got), np.zeros(4, bool))


def test_mark_members_degenerate_static_shapes():
    """M=0 and T=0 short-circuit to all-False of static shape [M]."""
    e = jnp.zeros(0, jnp.int32)
    a = (jnp.array([1, 2], jnp.int32), jnp.array([3, 4], jnp.int32))
    got_t0 = mark_members(a, (e, e), jnp.zeros(0, bool))
    assert got_t0.shape == (2,) and not bool(got_t0.any())
    got_m0 = mark_members((e, e), a, jnp.ones(2, bool))
    assert got_m0.shape == (0,)


def test_mark_members_keys_at_sentinel_minus_one():
    """SENTINEL-1 is the largest legal key value: it must match like any
    other key and never collide with the SENTINEL invalid marker."""
    big = SENTINEL - 1
    a = (jnp.array([big, big, 5], jnp.int32),
         jnp.array([big, 0, big], jnp.int32))
    b = (jnp.array([big, SENTINEL], jnp.int32),
         jnp.array([big, SENTINEL], jnp.int32))
    got = mark_members(a, b, jnp.array([True, True]))
    np.testing.assert_array_equal(np.asarray(got), [True, False, False])


@pytest.mark.parametrize("seed", range(4))
def test_mark_members_matches_reference_model(seed):
    rng = np.random.default_rng(40 + seed)
    m, t = int(rng.integers(1, 20)), int(rng.integers(1, 20))
    a = (jnp.asarray(rng.integers(0, 6, m), jnp.int32),
         jnp.asarray(rng.integers(0, 6, m), jnp.int32))
    b = (jnp.asarray(rng.integers(0, 6, t), jnp.int32),
         jnp.asarray(rng.integers(0, 6, t), jnp.int32))
    bv = jnp.asarray(rng.random(t) < 0.7)
    got = mark_members(a, b, bv)
    np.testing.assert_array_equal(np.asarray(got), _mark_ref(a, b, bv))
