"""K-fused rounds (one dispatch, one fetch) must be bit-identical to K
sequential fused steps — the dispatch-amortization that makes the
op->serializable-commit latency one backend round trip instead of
commit-lag round trips (VERDICT round-3 item 1)."""
import numpy as np

from janus_tpu.consensus import DagConfig
from janus_tpu.models import base, pncounter
from janus_tpu.runtime.safecrdt import SafeKV

N, W, B, K = 4, 8, 4, 8


def _kv():
    return SafeKV(DagConfig(N, W), pncounter.SPEC, ops_per_block=B,
                  num_keys=8, num_writers=N)


def _ops(rng, k=None):
    shape = (N, B) if k is None else (k, N, B)
    return base.make_op_batch(
        op=rng.integers(pncounter.OP_INC, pncounter.OP_DEC + 1, shape),
        key=rng.integers(0, 8, shape),
        a0=rng.integers(1, 5, shape),
        writer=np.broadcast_to(
            np.arange(N, dtype=np.int32)[None, :, None] if k else
            np.arange(N, dtype=np.int32)[:, None], shape).copy(),
    )


def test_step_k_matches_sequential_steps():
    rng_a = np.random.default_rng(7)
    rng_b = np.random.default_rng(7)
    a, b = _kv(), _kv()

    seq_infos = []
    for _ in range(3):
        ops_k = _ops(rng_a, K)
        for j in range(K):
            one = {f: ops_k[f][j] for f in ops_k}
            seq_infos.append(a.step(one, safe=np.ones((N, B), bool)))

    fused_infos = []
    for _ in range(3):
        ops_k = _ops(rng_b, K)
        safe_k = np.ones((K, N, B), bool)
        packed_k, metas = b.step_k_dispatch(ops_k, safe_k=safe_k)
        fused_infos.extend(b.step_k_absorb(packed_k, metas))

    # device states bit-identical
    for name in ("prospective", "stable", "dag", "commit", "ops_buffer"):
        ta, tb = getattr(a, name), getattr(b, name)
        for f in ta:
            np.testing.assert_array_equal(
                np.asarray(ta[f]), np.asarray(tb[f]), err_msg=f"{name}.{f}")
    # host observations identical round by round
    assert len(seq_infos) == len(fused_infos)
    for ia, ib in zip(seq_infos, fused_infos):
        np.testing.assert_array_equal(ia["accepted"], ib["accepted"])
        np.testing.assert_array_equal(ia["own"], ib["own"])
        np.testing.assert_array_equal(ia["recycled"], ib["recycled"])
    np.testing.assert_array_equal(a.commit_latencies(), b.commit_latencies())
    np.testing.assert_array_equal(a.safe_acks(), b.safe_acks())
    assert a.ordered_commits(0) == b.ordered_commits(0)
