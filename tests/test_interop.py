"""Golden-bytes wire interop with the reference's protobuf-net client.

The reference client serializes ClientMessage with protobuf-net:
``Serializer.SerializeWithLengthPrefix(stream, msg, PrefixStyle.Base128)``
(BFT-CRDT-Client/ServerConnection.cs:51) — a BARE varint length prefix
(fieldNumber=0, so no header tag) followed by a standard protobuf body
whose field numbers come from the [ProtoMember] attributes
(BFT-CRDT/Network/ClientMessages.cs:13-34):

    1 sourceType varint   2 sequenceNumber varint   3 key string
    4 typeCode string     5 opCode string           6 isSafe varint
    7 params repeated string   8 result varint BOOL  9 response string

The fixtures below are written as literal bytes, hand-derived from that
schema — NOT built with this repo's encoder — so they prove the native
parser accepts exactly what a protobuf-net client emits, and that our
replies parse under the reference's reply shape (result is the bool
field 8; the value text rides response, field 9 —
ClientInterface.CreateResponse, ClientInterface.cs:304-323).
"""
import socket
import time

import pytest

from janus_tpu.net.service import JanusConfig, JanusService, TypeConfig


def _recv_frames(sock, want, timeout=30.0):
    """Collect ``want`` bare-varint-length frames from the socket."""
    buf = bytearray()
    frames = []
    deadline = time.monotonic() + timeout
    sock.settimeout(1.0)
    while len(frames) < want:
        if time.monotonic() > deadline:
            raise TimeoutError(f"got {len(frames)}/{want} frames")
        try:
            chunk = sock.recv(65536)
        except socket.timeout:
            continue
        if not chunk:
            break
        buf.extend(chunk)
        while True:
            # bare varint length
            n, shift, off = 0, 0, 0
            complete = False
            while off < len(buf):
                b = buf[off]
                n |= (b & 0x7F) << shift
                shift += 7
                off += 1
                if not (b & 0x80):
                    complete = True
                    break
            if not complete or off + n > len(buf):
                break
            frames.append(bytes(buf[off: off + n]))
            del buf[: off + n]
    return frames


def _parse_reply(payload):
    """Minimal protobuf walk of a reply body: {seq, result_bool, response}."""
    out = {"seq": None, "result": None, "response": None}
    off = 0
    while off < len(payload):
        tag = 0
        shift = 0
        while True:
            b = payload[off]
            off += 1
            tag |= (b & 0x7F) << shift
            shift += 7
            if not (b & 0x80):
                break
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v = 0
            shift = 0
            while True:
                b = payload[off]
                off += 1
                v |= (b & 0x7F) << shift
                shift += 7
                if not (b & 0x80):
                    break
            if field == 2:
                out["seq"] = v
            elif field == 8:
                out["result"] = bool(v)
        elif wt == 2:
            n = 0
            shift = 0
            while True:
                b = payload[off]
                off += 1
                n |= (b & 0x7F) << shift
                shift += 7
                if not (b & 0x80):
                    break
            if field == 9:
                out["response"] = payload[off: off + n].decode()
            off += n
        else:
            pytest.fail(f"reply used unexpected wire type {wt}")
    return out


# Hand-encoded protobuf-net request frames (sourceType=Client(1)).
# create: seq=1 key="acct" typeCode="pnc" opCode="s"
CREATE = bytes([
    0x12,                                      # bare varint length = 18
    0x08, 0x01,                                # 1: sourceType = 1
    0x10, 0x01,                                # 2: seq = 1
    0x1A, 0x04, 0x61, 0x63, 0x63, 0x74,        # 3: key = "acct"
    0x22, 0x03, 0x70, 0x6E, 0x63,              # 4: typeCode = "pnc"
    0x2A, 0x01, 0x73,                          # 5: opCode = "s"
])
# increment: seq=2 opCode="i" params=["5"]
INCR = bytes([
    0x15,                                      # length = 21
    0x08, 0x01,
    0x10, 0x02,                                # 2: seq = 2
    0x1A, 0x04, 0x61, 0x63, 0x63, 0x74,
    0x22, 0x03, 0x70, 0x6E, 0x63,
    0x2A, 0x01, 0x69,                          # 5: opCode = "i"
    0x3A, 0x01, 0x35,                          # 7: params[0] = "5"
])
# prospective read: seq=3 opCode="gp"
READ = bytes([
    0x13,                                      # length = 19
    0x08, 0x01,
    0x10, 0x03,                                # 2: seq = 3
    0x1A, 0x04, 0x61, 0x63, 0x63, 0x74,
    0x22, 0x03, 0x70, 0x6E, 0x63,
    0x2A, 0x02, 0x67, 0x70,                    # 5: opCode = "gp"
])
# update on a never-created key -> error reply: seq=4 key="ghost"
GHOST = bytes([
    0x16,                                      # length = 22
    0x08, 0x01,
    0x10, 0x04,                                # 2: seq = 4
    0x1A, 0x05, 0x67, 0x68, 0x6F, 0x73, 0x74,  # 3: key = "ghost"
    0x22, 0x03, 0x70, 0x6E, 0x63,
    0x2A, 0x01, 0x69,
    0x3A, 0x01, 0x35,
])


def test_protobuf_net_golden_bytes():
    cfg = JanusConfig(num_nodes=4, window=8, ops_per_block=8,
                      types=(TypeConfig("pnc", {"num_keys": 8}),))
    with JanusService(cfg) as svc:
        with socket.create_connection(("127.0.0.1", svc.server.port),
                                      timeout=30) as sock:
            sock.sendall(CREATE)
            frames = _recv_frames(sock, 1)
            create_rep = _parse_reply(frames[0])
            assert create_rep["seq"] == 1
            assert create_rep["result"] is True

            sock.sendall(INCR + READ + GHOST)
            replies = [_parse_reply(f) for f in _recv_frames(sock, 3)]
            by_seq = {r["seq"]: r for r in replies}
            assert set(by_seq) == {2, 3, 4}
            # unsafe update: result=true (the bool, field 8)
            assert by_seq[2]["result"] is True
            # read: the VALUE rides response (field 9), like the
            # reference's output string
            assert by_seq[3]["result"] is True
            assert by_seq[3]["response"] == "5"
            # unknown key: result=false + error text in response
            assert by_seq[4]["result"] is False
            assert "error" in by_seq[4]["response"]
