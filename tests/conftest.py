"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's "multi-node without a cluster" test strategy
(reference Tests/KVStoreTests.cs:16-80 runs 4 full server stacks in one
process); here the analog is N virtual XLA CPU devices in one process.
Must run before any jax import.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
