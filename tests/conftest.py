"""Test harness: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's "multi-node without a cluster" test strategy
(reference Tests/KVStoreTests.cs:16-80 runs 4 full server stacks in one
process); here the analog is N virtual XLA CPU devices in one process.

The env vars must be set before jax import; the config.update handles
environments where a site hook (e.g. a TPU-tunnel plugin) force-registers
another platform ahead of CPU regardless of JAX_PLATFORMS.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def native_lib():
    """Build-or-skip gate for tests that exercise the native runtime.

    ``binding.load()`` rebuilds libjanus_native.so whenever any native
    source is newer than the binary (and the Makefile's -MMD deps keep
    the object cache honest), so a test that takes this fixture can
    never run against a stale .so — the failure mode that makes native
    changes look like test flakes. When the toolchain is absent the
    dependent tests SKIP with the build error instead of failing."""
    from janus_tpu.net import binding
    try:
        return binding.load()
    except Exception as e:  # missing g++ / failed compile
        pytest.skip(f"native runtime unavailable: {e}")
